"""TFLite model importer: serve the reference's ``.tflite`` files natively.

The reference's flagship model format is an opaque ``.tflite`` flatbuffer
served through the TFLite Interpreter (``tensor_filter_tensorflow_lite.cc:154``
class TFLiteInterpreter; its golden pipelines pass
``model=mobilenet_v2_1.0_224_quant.tflite`` etc.).  There is no TFLite
runtime in this stack — and running an interpreter would be the wrong design
for TPU anyway.  Instead this module:

1. parses the flatbuffer **directly** (a ~150-line generic reader over the
   wire format — same skill as ``converters/fb_io.py``, applied to the
   public TFLite schema, field ids documented inline), and
2. **lowers the op graph to one JAX function** compiled by XLA, with
   weights exposed as a params pytree (hot-reload / donation friendly).

Quantized (uint8) models execute in *dequantized float*: weights are
dequantized at load time (per-tensor or per-channel ``scale``/``zero_point``),
the input is dequantized inside the XLA program, and outputs are requantized
to the model's stated uint8 contract — so the pipeline sees exactly the
reference caps (e.g. in uint8 3:224:224:1, out uint8 1001:1) while the MXU
runs large float convolutions.  This intentionally trades tflite's bit-exact
integer requantization for XLA-fusable float math; classification/seg
results match (golden: the reference's orange.png classifies to "orange",
``tests/test_tflite_import.py``).

Op coverage targets the reference's shipped models
(``mobilenet_v2_1.0_224_quant.tflite``, ``deeplabv3_257_mv_gpu.tflite``,
``add.tflite``) plus the common mobile-vision subset around them.
"""

from __future__ import annotations

import os
import struct
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core.log import logger
from ..core.types import TensorInfo, TensorsInfo
from .zoo import ModelBundle

log = logger("tflite")

# --------------------------------------------------------------------------- #
# Generic flatbuffer reader (little-endian wire format, flatbuffers.md spec)
# --------------------------------------------------------------------------- #


class _FB:
    """Minimal flatbuffer accessor: tables, vtables, scalars, vectors,
    strings. Positions are absolute byte offsets into ``buf``."""

    __slots__ = ("buf",)

    def __init__(self, buf: bytes) -> None:
        self.buf = buf

    # scalar readers
    def u8(self, p): return self.buf[p]
    def i8(self, p): return struct.unpack_from("<b", self.buf, p)[0]
    def u16(self, p): return struct.unpack_from("<H", self.buf, p)[0]
    def i32(self, p): return struct.unpack_from("<i", self.buf, p)[0]
    def u32(self, p): return struct.unpack_from("<I", self.buf, p)[0]
    def i64(self, p): return struct.unpack_from("<q", self.buf, p)[0]
    def f32(self, p): return struct.unpack_from("<f", self.buf, p)[0]

    def root(self) -> int:
        """Root table position (file identifier, if any, is skipped)."""
        return self.u32(0)

    def indirect(self, p: int) -> int:
        return p + self.u32(p)

    def field(self, table: int, fid: int) -> int:
        """Byte offset of field ``fid`` within ``table``, or 0 if absent
        (vtable lookup: soffset at table start points BACK to the vtable;
        slot for field id N sits at vtable + 4 + 2N)."""
        vtable = table - self.i32(table)
        vsize = self.u16(vtable)
        slot = 4 + 2 * fid
        if slot >= vsize:
            return 0
        off = self.u16(vtable + slot)
        return table + off if off else 0

    def scalar(self, table: int, fid: int, reader: Callable[[int], Any],
               default: Any) -> Any:
        p = self.field(table, fid)
        return reader(p) if p else default

    def offset(self, table: int, fid: int) -> Optional[int]:
        """Position of an offset-typed field's target (string/vector/table)."""
        p = self.field(table, fid)
        return self.indirect(p) if p else None

    def string(self, table: int, fid: int) -> Optional[str]:
        p = self.offset(table, fid)
        if p is None:
            return None
        n = self.u32(p)
        return self.buf[p + 4:p + 4 + n].decode("utf-8", "replace")

    def vector(self, table: int, fid: int) -> Optional[Tuple[int, int]]:
        """(element count, position of first element) or None."""
        p = self.offset(table, fid)
        if p is None:
            return None
        return self.u32(p), p + 4

    def vec_np(self, table: int, fid: int, dtype: str) -> Optional[np.ndarray]:
        v = self.vector(table, fid)
        if v is None:
            return None
        n, p = v
        return np.frombuffer(self.buf, dtype=dtype, count=n, offset=p).copy()

    def vec_tables(self, table: int, fid: int) -> List[int]:
        """Positions of tables in a vector-of-tables field."""
        v = self.vector(table, fid)
        if v is None:
            return []
        n, p = v
        return [self.indirect(p + 4 * i) for i in range(n)]


# --------------------------------------------------------------------------- #
# TFLite schema walk (field ids per the public tensorflow/lite schema.fbs)
# --------------------------------------------------------------------------- #

#: schema TensorType enum → numpy dtype
_TENSORTYPE_NP = {
    0: np.float32, 1: np.float16, 2: np.int32, 3: np.uint8, 4: np.int64,
    6: np.bool_, 7: np.int16, 9: np.int8, 10: np.float64,
    16: np.uint32, 17: np.uint16,
}

#: deprecated_builtin_code → op name (subset; stable public enum)
_BUILTIN_OPS = {
    0: "ADD", 1: "AVERAGE_POOL_2D", 2: "CONCATENATION", 3: "CONV_2D",
    4: "DEPTHWISE_CONV_2D", 5: "DEPTH_TO_SPACE", 6: "DEQUANTIZE",
    9: "FULLY_CONNECTED", 14: "LOGISTIC", 17: "MAX_POOL_2D", 18: "MUL",
    19: "RELU", 21: "RELU6", 22: "RESHAPE", 23: "RESIZE_BILINEAR",
    25: "SOFTMAX", 26: "SPACE_TO_DEPTH", 28: "TANH", 32: "CUSTOM",
    34: "PAD", 36: "GATHER", 39: "TRANSPOSE", 40: "MEAN", 41: "SUB",
    42: "DIV", 43: "SQUEEZE", 45: "STRIDED_SLICE", 47: "EXP",
    49: "SPLIT", 53: "CAST", 54: "PRELU", 55: "MAXIMUM", 56: "ARG_MAX",
    57: "MINIMUM", 58: "LESS", 60: "PAD_V2", 61: "GREATER",
    62: "GREATER_EQUAL", 63: "LESS_EQUAL", 65: "SLICE",
    67: "TRANSPOSE_CONV", 70: "EXPAND_DIMS", 71: "EQUAL", 72: "NOT_EQUAL",
    73: "LOG", 74: "SUM", 75: "SQRT", 76: "RSQRT", 77: "SHAPE",
    78: "POW", 79: "ARG_MIN", 82: "REDUCE_MAX", 83: "PACK",
    84: "LOGICAL_OR", 86: "LOGICAL_AND", 87: "LOGICAL_NOT",
    88: "UNPACK", 89: "REDUCE_MIN", 97: "RESIZE_NEAREST",
    98: "LEAKY_RELU", 101: "ABS", 114: "QUANTIZE", 117: "HARD_SWISH",
    118: "IF", 119: "WHILE",
}

_ACT_NONE, _ACT_RELU, _ACT_RELU_N1, _ACT_RELU6, _ACT_TANH = 0, 1, 2, 3, 4

#: CUSTOM ops the lowerer handles (others fail at load)
_SUPPORTED_CUSTOM = frozenset({"CUSTOM:TFLite_Detection_PostProcess"})


@dataclass
class QuantParams:
    """Per-tensor (or per-channel along ``axis``) affine quantization:
    real = scale * (q - zero_point)."""

    scale: np.ndarray          # shape () or (C,)
    zero_point: np.ndarray     # same shape, int64
    axis: int = 0              # quantized_dimension for per-channel

    @property
    def per_channel(self) -> bool:
        return self.scale.ndim > 0 and self.scale.size > 1


@dataclass
class TFLTensor:
    index: int
    name: str
    shape: Tuple[int, ...]
    np_dtype: Any
    buffer_index: int
    quant: Optional[QuantParams]
    data: Optional[np.ndarray] = None   # constant payload (typed, undequantized)


@dataclass
class TFLOperator:
    op: str                              # name from _BUILTIN_OPS / custom code
    inputs: List[int]                    # tensor indices (-1 = absent optional)
    outputs: List[int]
    options: Dict[str, Any] = field(default_factory=dict)


@dataclass
class TFLSubgraph:
    tensors: List[TFLTensor]
    operators: List[TFLOperator]
    inputs: List[int]
    outputs: List[int]
    name: str = ""


@dataclass
class TFLModel:
    path: str
    version: int
    description: str
    #: main subgraph contents, aliased for the common single-graph case
    tensors: List[TFLTensor]
    operators: List[TFLOperator]
    inputs: List[int]
    outputs: List[int]
    #: ALL subgraphs (index 0 is the main one above); >1 for control-flow
    #: models (IF/WHILE bodies live in their own subgraphs)
    subgraphs: List[TFLSubgraph] = field(default_factory=list)


def _parse_quant(fb: _FB, qpos: Optional[int]) -> Optional[QuantParams]:
    # QuantizationParameters: 0 min, 1 max, 2 scale[f32], 3 zero_point[i64],
    # 4 details(union: ids 4+5), 6 quantized_dimension
    if qpos is None:
        return None
    scale = fb.vec_np(qpos, 2, "<f4")
    if scale is None or scale.size == 0:
        return None
    zp = fb.vec_np(qpos, 3, "<i8")
    if zp is None or zp.size == 0:
        zp = np.zeros_like(scale, dtype=np.int64)
    axis = fb.scalar(qpos, 6, fb.i32, 0)
    if scale.size == 1:
        scale, zp = scale.reshape(()), zp.reshape(())
    return QuantParams(scale, zp, axis)


def _parse_options(fb: _FB, op: str, opos: Optional[int]) -> Dict[str, Any]:
    """Builtin options table → dict, dispatched on the op (the union type
    field is redundant with the opcode for the supported subset)."""
    o: Dict[str, Any] = {}
    if opos is None:
        # no builtin_options table at all: every field is schema-default,
        # which for conv/pool means stride 0 — fall through so the
        # prepare-time stride/filter guard below reports it clearly
        return _validate_options(op, o)
    if op == "CONV_2D":
        # Conv2DOptions: 0 padding, 1 stride_w, 2 stride_h, 3 activation,
        # 4 dilation_w, 5 dilation_h
        o["padding"] = fb.scalar(opos, 0, fb.i8, 0)
        o["stride_w"] = fb.scalar(opos, 1, fb.i32, 0)
        o["stride_h"] = fb.scalar(opos, 2, fb.i32, 0)
        o["activation"] = fb.scalar(opos, 3, fb.i8, 0)
        o["dilation_w"] = fb.scalar(opos, 4, fb.i32, 1)
        o["dilation_h"] = fb.scalar(opos, 5, fb.i32, 1)
    elif op == "DEPTHWISE_CONV_2D":
        # DepthwiseConv2DOptions: 0 padding, 1 stride_w, 2 stride_h,
        # 3 depth_multiplier, 4 activation, 5 dilation_w, 6 dilation_h
        o["padding"] = fb.scalar(opos, 0, fb.i8, 0)
        o["stride_w"] = fb.scalar(opos, 1, fb.i32, 0)
        o["stride_h"] = fb.scalar(opos, 2, fb.i32, 0)
        o["depth_multiplier"] = fb.scalar(opos, 3, fb.i32, 0)
        o["activation"] = fb.scalar(opos, 4, fb.i8, 0)
        o["dilation_w"] = fb.scalar(opos, 5, fb.i32, 1)
        o["dilation_h"] = fb.scalar(opos, 6, fb.i32, 1)
    elif op in ("AVERAGE_POOL_2D", "MAX_POOL_2D"):
        # Pool2DOptions: 0 padding, 1 stride_w, 2 stride_h, 3 filter_width,
        # 4 filter_height, 5 activation
        o["padding"] = fb.scalar(opos, 0, fb.i8, 0)
        o["stride_w"] = fb.scalar(opos, 1, fb.i32, 0)
        o["stride_h"] = fb.scalar(opos, 2, fb.i32, 0)
        o["filter_w"] = fb.scalar(opos, 3, fb.i32, 0)
        o["filter_h"] = fb.scalar(opos, 4, fb.i32, 0)
        o["activation"] = fb.scalar(opos, 5, fb.i8, 0)
    elif op == "SOFTMAX":
        o["beta"] = fb.scalar(opos, 0, fb.f32, 1.0)
    elif op == "CONCATENATION":
        o["axis"] = fb.scalar(opos, 0, fb.i32, 0)
        o["activation"] = fb.scalar(opos, 1, fb.i8, 0)
    elif op in ("ADD", "MUL", "SUB", "DIV"):
        o["activation"] = fb.scalar(opos, 0, fb.i8, 0)
    elif op == "RESHAPE":
        ns = fb.vec_np(opos, 0, "<i4")
        if ns is not None:
            o["new_shape"] = [int(x) for x in ns]
    elif op == "RESIZE_BILINEAR":
        # ResizeBilinearOptions: 0/1 deprecated new_h/new_w,
        # 2 align_corners, 3 half_pixel_centers
        o["align_corners"] = bool(fb.scalar(opos, 2, fb.u8, 0))
        o["half_pixel_centers"] = bool(fb.scalar(opos, 3, fb.u8, 0))
    elif op == "RESIZE_NEAREST":
        # ResizeNearestNeighborOptions: 0 align_corners, 1 half_pixel_centers
        o["align_corners"] = bool(fb.scalar(opos, 0, fb.u8, 0))
        o["half_pixel_centers"] = bool(fb.scalar(opos, 1, fb.u8, 0))
    elif op == "FULLY_CONNECTED":
        o["activation"] = fb.scalar(opos, 0, fb.i8, 0)
        o["keep_num_dims"] = bool(fb.scalar(opos, 2, fb.u8, 0))
    elif op in ("MEAN", "SUM", "REDUCE_MAX", "REDUCE_MIN"):
        o["keep_dims"] = bool(fb.scalar(opos, 0, fb.u8, 0))
    elif op in ("ARG_MAX", "ARG_MIN"):
        o["output_type"] = fb.scalar(opos, 0, fb.i8, 2)  # TensorType enum
    elif op == "SQUEEZE":
        sq = fb.vec_np(opos, 0, "<i4")
        o["squeeze_dims"] = [] if sq is None else [int(x) for x in sq]
    elif op == "STRIDED_SLICE":
        for i, k in enumerate(("begin_mask", "end_mask", "ellipsis_mask",
                               "new_axis_mask", "shrink_axis_mask")):
            o[k] = fb.scalar(opos, i, fb.i32, 0)
    elif op == "TRANSPOSE_CONV":
        # TransposeConvOptions: 0 padding, 1 stride_w, 2 stride_h
        # (later schema adds fused_activation at 3; default NONE)
        o["padding"] = fb.scalar(opos, 0, fb.i8, 0)
        o["stride_w"] = fb.scalar(opos, 1, fb.i32, 0)
        o["stride_h"] = fb.scalar(opos, 2, fb.i32, 0)
        o["activation"] = fb.scalar(opos, 3, fb.i8, 0)
    elif op == "GATHER":
        # GatherOptions: 0 axis, 1 batch_dims
        o["axis"] = fb.scalar(opos, 0, fb.i32, 0)
        o["batch_dims"] = fb.scalar(opos, 1, fb.i32, 0)
    elif op == "UNPACK":
        # UnpackOptions: 0 num (validated against the output count in the
        # lowerer), 1 axis
        o["num"] = fb.scalar(opos, 0, fb.i32, 0)
        o["axis"] = fb.scalar(opos, 1, fb.i32, 0)
    elif op == "LEAKY_RELU":
        o["alpha"] = fb.scalar(opos, 0, fb.f32, 0.0)
    elif op in ("DEPTH_TO_SPACE", "SPACE_TO_DEPTH"):
        o["block_size"] = fb.scalar(opos, 0, fb.i32, 1)
    elif op == "CAST":
        # CastOptions: 0 in_data_type, 1 out_data_type; the table is
        # commonly omitted (dtype inferable from the output tensor) —
        # keep None in that case so the evaluator falls back correctly
        p = fb.field(opos, 1)
        if p:
            o["out_type"] = fb.i8(p)
    elif op == "PACK":
        # PackOptions: 0 values_count, 1 axis
        o["axis"] = fb.scalar(opos, 1, fb.i32, 0)
    elif op == "IF":
        # IfOptions: 0 then_subgraph_index, 1 else_subgraph_index
        o["then_subgraph"] = fb.scalar(opos, 0, fb.i32, 0)
        o["else_subgraph"] = fb.scalar(opos, 1, fb.i32, 0)
    elif op == "WHILE":
        # WhileOptions: 0 cond_subgraph_index, 1 body_subgraph_index
        o["cond_subgraph"] = fb.scalar(opos, 0, fb.i32, 0)
        o["body_subgraph"] = fb.scalar(opos, 1, fb.i32, 0)
    return _validate_options(op, o)


def _validate_options(op: str, o: Dict[str, Any]) -> Dict[str, Any]:
    """Prepare-time checks the TFLite runtime also makes
    (tflite/kernels/conv.cc:378): the schema stride/filter default is 0,
    so a writer must set them explicitly."""
    if op in ("CONV_2D", "DEPTHWISE_CONV_2D", "AVERAGE_POOL_2D",
              "MAX_POOL_2D", "TRANSPOSE_CONV"):
        if o.get("stride_w", 0) < 1 or o.get("stride_h", 0) < 1:
            raise ValueError(
                f"tflite: {op} stride_w/stride_h must be >= 1 "
                f"(got {o.get('stride_w')}x{o.get('stride_h')})")
    if op in ("AVERAGE_POOL_2D", "MAX_POOL_2D"):
        if o.get("filter_w", 0) < 1 or o.get("filter_h", 0) < 1:
            raise ValueError(
                f"tflite: {op} filter_width/filter_height must be >= 1 "
                f"(got {o.get('filter_w')}x{o.get('filter_h')})")
    if op == "IF" and (o.get("then_subgraph", 0) < 1
                       or o.get("else_subgraph", 0) < 1):
        # a missing/defaulted options table would point the branch at
        # subgraph 0 — the MAIN graph, i.e. unbounded self-recursion —
        # reject malformed control flow at parse
        raise ValueError(
            "tflite: IF operator missing/invalid then/else subgraph indices")
    if op == "WHILE" and (o.get("cond_subgraph", 0) < 1
                          or o.get("body_subgraph", 0) < 1):
        raise ValueError(
            "tflite: WHILE operator missing/invalid cond/body subgraph "
            "indices")
    return o


def parse_tflite(path: str) -> TFLModel:
    """Parse a .tflite flatbuffer (single-subgraph) into a TFLModel."""
    with open(path, "rb") as f:
        buf = f.read()
    if len(buf) < 8:
        raise ValueError(f"{path}: not a tflite flatbuffer (too small)")
    ident = buf[4:8]
    if ident not in (b"TFL3", b"TFL2", b"TFL1"):
        raise ValueError(f"{path}: missing TFL3 file identifier "
                         f"(got {ident!r})")
    fb = _FB(buf)
    # Model: 0 version, 1 operator_codes, 2 subgraphs, 3 description,
    # 4 buffers, 5 metadata_buffer, 6 metadata, 7 signature_defs
    model = fb.root()
    version = fb.scalar(model, 0, fb.u32, 0)
    desc = fb.string(model, 3) or ""

    # operator codes → names
    op_names: List[str] = []
    for oc in fb.vec_tables(model, 1):
        # OperatorCode: 0 deprecated_builtin_code(i8), 1 custom_code,
        # 2 version, 3 builtin_code(i32, post-2020 codes >127)
        code = fb.scalar(oc, 3, fb.i32, 0) or fb.scalar(oc, 0, fb.i8, 0)
        if code == 32:  # CUSTOM
            op_names.append("CUSTOM:" + (fb.string(oc, 1) or "?"))
        else:
            op_names.append(_BUILTIN_OPS.get(code, f"UNKNOWN_{code}"))

    # buffers (0 data:[ubyte])
    buffers: List[Optional[Tuple[int, int]]] = []
    for b in fb.vec_tables(model, 4):
        buffers.append(fb.vector(b, 0))  # (nbytes, pos) or None

    def parse_subgraph(sg) -> TFLSubgraph:
        # SubGraph: 0 tensors, 1 inputs, 2 outputs, 3 operators, 4 name
        tensors: List[TFLTensor] = []
        for i, t in enumerate(fb.vec_tables(sg, 0)):
            # Tensor: 0 shape[i32], 1 type(i8), 2 buffer(u32), 3 name,
            # 4 quantization, 5 is_variable, 6 sparsity, 7 shape_signature
            shape_v = fb.vec_np(t, 0, "<i4")
            shape = tuple(int(d) for d in shape_v) \
                if shape_v is not None else ()
            ttype = fb.scalar(t, 1, fb.i8, 0)
            np_dtype = _TENSORTYPE_NP.get(ttype)
            if np_dtype is None:
                raise ValueError(f"{path}: tensor {i} has unsupported "
                                 f"TensorType {ttype}")
            bufidx = fb.scalar(t, 2, fb.u32, 0)
            quant = _parse_quant(fb, fb.offset(t, 4))
            data = None
            if 0 < bufidx < len(buffers) and buffers[bufidx] is not None:
                nbytes, pos = buffers[bufidx]
                if nbytes:
                    flat = np.frombuffer(
                        buf, dtype=np.dtype(np_dtype),
                        count=nbytes // np.dtype(np_dtype).itemsize,
                        offset=pos)
                    data = flat.reshape(shape if shape else (-1,)).copy()
            tensors.append(TFLTensor(i, fb.string(t, 3) or f"t{i}", shape,
                                     np_dtype, bufidx, quant, data))

        operators: List[TFLOperator] = []
        for opr in fb.vec_tables(sg, 3):
            # Operator: 0 opcode_index, 1 inputs[i32], 2 outputs[i32],
            # 3 builtin_options_type(u8), 4 builtin_options(table),
            # 5 custom_options[ubyte]
            idx = fb.scalar(opr, 0, fb.u32, 0)
            name = op_names[idx] if idx < len(op_names) else f"BADCODE_{idx}"
            ins = fb.vec_np(opr, 1, "<i4")
            outs = fb.vec_np(opr, 2, "<i4")
            options = _parse_options(fb, name, fb.offset(opr, 4))
            if name.startswith("CUSTOM:"):
                # Operator slot 5: custom_options[ubyte] — a flexbuffer map
                # for the ops we support (the flatbuffers *runtime* decodes
                # it; no generated code involved)
                co = fb.vector(opr, 5)
                if co is not None:
                    nbytes, pos = co
                    if nbytes:
                        try:
                            from flatbuffers import flexbuffers

                            decoded = flexbuffers.Loads(
                                bytes(buf[pos:pos + nbytes]))
                            if isinstance(decoded, dict):
                                options.update(decoded)
                        except Exception:
                            pass  # op lowering reports missing keys clearly
            operators.append(TFLOperator(
                name, [int(x) for x in (ins if ins is not None else [])],
                [int(x) for x in (outs if outs is not None else [])],
                options))

        inputs_v = fb.vec_np(sg, 1, "<i4")
        outputs_v = fb.vec_np(sg, 2, "<i4")
        return TFLSubgraph(
            tensors, operators,
            [int(x) for x in (inputs_v if inputs_v is not None else [])],
            [int(x) for x in (outputs_v if outputs_v is not None else [])],
            fb.string(sg, 4) or "")

    sg_tables = fb.vec_tables(model, 2)
    if not sg_tables:
        raise ValueError(f"{path}: model has no subgraphs")
    parsed = [parse_subgraph(sg) for sg in sg_tables]
    main = parsed[0]
    return TFLModel(path, version, desc, main.tensors, main.operators,
                    main.inputs, main.outputs, parsed)


# --------------------------------------------------------------------------- #
# Lowering: TFLite op graph → one JAX function
# --------------------------------------------------------------------------- #


def _require_per_tensor_io(m: "TFLModel", t: TFLTensor, role: str) -> None:
    """Graph I/O (de/re)quantization supports per-tensor quant only —
    per-channel scales on an I/O tensor would need a layout contract the
    uint8 wire caps cannot express."""
    if t.quant is not None and t.quant.per_channel:
        raise NotImplementedError(
            f"{os.path.basename(m.path)}: graph {role} tensor {t.name!r} is "
            "per-channel quantized; only per-tensor-quantized model I/O is "
            "supported")


def _dequant_const(t: TFLTensor) -> np.ndarray:
    """Constant tensor → float32 (weights/bias of quantized models are
    dequantized once at load; float constants pass through)."""
    a = t.data
    assert a is not None
    if np.issubdtype(a.dtype, np.floating):
        return a.astype(np.float32)
    if t.quant is None:
        return a  # integer constant used as shape/axes — keep typed
    q = t.quant
    if q.per_channel:
        # broadcast scale along quantized_dimension
        bshape = [1] * a.ndim
        bshape[q.axis] = q.scale.size
        scale = q.scale.reshape(bshape)
        zp = q.zero_point.reshape(bshape)
    else:
        scale, zp = q.scale, q.zero_point
    return ((a.astype(np.float32) - zp.astype(np.float32))
            * scale.astype(np.float32))


def _fused_act(x, code: int):
    import jax.numpy as jnp

    if code == _ACT_NONE:
        return x
    if code == _ACT_RELU:
        return jnp.maximum(x, 0.0)
    if code == _ACT_RELU_N1:
        return jnp.clip(x, -1.0, 1.0)
    if code == _ACT_RELU6:
        return jnp.clip(x, 0.0, 6.0)
    if code == _ACT_TANH:
        return jnp.tanh(x)
    raise ValueError(f"unsupported fused activation {code}")


_PAD_MODES = {0: "SAME", 1: "VALID"}


def _resize_bilinear(x, out_h: int, out_w: int, align_corners: bool,
                     half_pixel: bool):
    """Gather-based bilinear resize matching TFLite's coordinate
    conventions (align_corners / half_pixel_centers), NHWC."""
    import jax.numpy as jnp

    n, h, w, c = x.shape
    if align_corners and out_h > 1:
        ys = jnp.arange(out_h, dtype=jnp.float32) * ((h - 1) / (out_h - 1))
    elif half_pixel:
        ys = (jnp.arange(out_h, dtype=jnp.float32) + 0.5) * (h / out_h) - 0.5
    else:
        ys = jnp.arange(out_h, dtype=jnp.float32) * (h / out_h)
    if align_corners and out_w > 1:
        xs = jnp.arange(out_w, dtype=jnp.float32) * ((w - 1) / (out_w - 1))
    elif half_pixel:
        xs = (jnp.arange(out_w, dtype=jnp.float32) + 0.5) * (w / out_w) - 0.5
    else:
        xs = jnp.arange(out_w, dtype=jnp.float32) * (w / out_w)
    ys = jnp.clip(ys, 0.0, h - 1)
    xs = jnp.clip(xs, 0.0, w - 1)
    y0 = jnp.floor(ys).astype(jnp.int32)
    x0 = jnp.floor(xs).astype(jnp.int32)
    y1 = jnp.minimum(y0 + 1, h - 1)
    x1 = jnp.minimum(x0 + 1, w - 1)
    wy = (ys - y0)[None, :, None, None]
    wx = (xs - x0)[None, None, :, None]
    a = x[:, y0][:, :, x0]
    b = x[:, y0][:, :, x1]
    cc = x[:, y1][:, :, x0]
    d = x[:, y1][:, :, x1]
    return (a * (1 - wy) * (1 - wx) + b * (1 - wy) * wx
            + cc * wy * (1 - wx) + d * wy * wx)


def _avg_pool_same_countvalid(x, fh, fw, sh, sw):
    """AVERAGE_POOL_2D with SAME padding counts only in-bounds elements
    (TFLite semantics); implemented as sum-pool / ones-pool."""
    import jax.numpy as jnp
    from jax import lax

    ones = jnp.ones(x.shape[:1] + x.shape[1:3] + (1,), x.dtype)
    dims = (1, fh, fw, 1)
    strides = (1, sh, sw, 1)
    s = lax.reduce_window(x, 0.0, lax.add, dims, strides, "SAME")
    n = lax.reduce_window(ones, 0.0, lax.add, dims, strides, "SAME")
    return s / n


class _Lowerer:
    """Per-subgraph lowering state: maps tensor index → traced value.

    The root lowerer (subgraph 0) owns the shared params dict and eagerly
    creates child lowerers for every other subgraph, so ALL constants are
    registered before the first jit trace flattens the params pytree
    (IF/WHILE bodies live in their own subgraphs and run via
    lax.cond/lax.while_loop)."""

    def __init__(self, m: TFLModel, sg_index: int = 0,
                 root: Optional["_Lowerer"] = None):
        self.m = m
        self.sg = m.subgraphs[sg_index] if m.subgraphs else m
        self.sg_index = sg_index
        self._prefix = "" if sg_index == 0 else f"sg{sg_index}/"
        self.root = root or self
        self.params: Dict[str, np.ndarray] = \
            {} if root is None else root.params
        self.const_idx: set = set()
        for t in self.sg.tensors:
            if t.data is not None:
                self.params[f"{self._prefix}t{t.index}"] = _dequant_const(t)
                self.const_idx.add(t.index)
                t.data = None  # raw payload no longer needed; the params
                # copy is the only one that must outlive the load
        if root is None:
            self._children: Dict[int, "_Lowerer"] = {0: self}
            for si in range(1, len(m.subgraphs or [])):
                self._children[si] = _Lowerer(m, si, root=self)

    def _subgraph_apply(self, si: int) -> Callable:
        try:
            child = self.root._children[si]
        except KeyError:
            raise ValueError(
                f"{os.path.basename(self.m.path)}: control-flow op "
                f"references unknown subgraph {si}") from None
        return child.build_apply()

    # -- graph evaluation --------------------------------------------------- #
    def build_apply(self) -> Callable:
        m = self.m
        sg = self.sg
        const_idx = self.const_idx
        prefix = self._prefix
        is_root = self.root is self

        def apply(params, *inputs):
            import jax.numpy as jnp

            env: Dict[Any, Any] = {}
            # live params ride in the env so IF/WHILE evals can pass them
            # to child subgraph applies explicitly (no mutable lowerer
            # state — a stashed pytree would retain dead tracers)
            env["__params__"] = params
            for idx in const_idx:
                env[idx] = params[f"{prefix}t{idx}"]
            if len(inputs) != len(sg.inputs):
                raise ValueError(
                    f"{os.path.basename(m.path)}: expected "
                    f"{len(sg.inputs)} inputs, got {len(inputs)}")
            for idx, x in zip(sg.inputs, inputs):
                t = sg.tensors[idx]
                x = jnp.asarray(x)
                if x.shape != t.shape and int(np.prod(x.shape)) == int(
                        np.prod(t.shape)):
                    x = x.reshape(t.shape)
                if is_root and t.quant is not None and not np.issubdtype(
                        np.dtype(t.np_dtype), np.floating):
                    # model-BOUNDARY dequantization only: inner subgraphs
                    # (IF/WHILE bodies) receive already-dequantized floats
                    _require_per_tensor_io(m, t, "input")
                    x = (x.astype(jnp.float32)
                         - np.float32(t.quant.zero_point)) \
                        * np.float32(t.quant.scale)
                env[idx] = x
            for op in sg.operators:
                self._eval_op(op, env)
            outs = []
            for idx in sg.outputs:
                t = sg.tensors[idx]
                y = env[idx]
                if is_root and t.quant is not None and not np.issubdtype(
                        np.dtype(t.np_dtype), np.floating):
                    _require_per_tensor_io(m, t, "output")
                    q = jnp.round(y / np.float32(t.quant.scale)
                                  + np.float32(t.quant.zero_point))
                    info = np.iinfo(t.np_dtype)
                    y = jnp.clip(q, info.min, info.max).astype(t.np_dtype)
                outs.append(y)
            return tuple(outs)

        return apply

    def _eval_op(self, op: TFLOperator, env: Dict[int, Any]) -> None:
        import jax.numpy as jnp
        from jax import lax

        o = op.options
        get = lambda i: env[op.inputs[i]] if (  # noqa: E731
            i < len(op.inputs) and op.inputs[i] >= 0) else None

        name = op.op
        if name == "CONV_2D":
            x, w, b = get(0), get(1), get(2)
            # tflite kernel is OHWI → HWIO for lax
            w = jnp.transpose(w, (1, 2, 3, 0))
            y = lax.conv_general_dilated(
                x, w, (o["stride_h"], o["stride_w"]),
                _PAD_MODES[o["padding"]],
                rhs_dilation=(o["dilation_h"], o["dilation_w"]),
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
            if b is not None:
                y = y + b
            y = _fused_act(y, o["activation"])
        elif name == "DEPTHWISE_CONV_2D":
            x, w, b = get(0), get(1), get(2)
            # tflite dw kernel is (1, H, W, in*mult) → HWIO w/ I=1
            cin = x.shape[-1]
            w = jnp.transpose(w, (1, 2, 0, 3))  # H W 1 C
            y = lax.conv_general_dilated(
                x, w, (o["stride_h"], o["stride_w"]),
                _PAD_MODES[o["padding"]],
                rhs_dilation=(o["dilation_h"], o["dilation_w"]),
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
                feature_group_count=cin)
            if b is not None:
                y = y + b
            y = _fused_act(y, o["activation"])
        elif name == "AVERAGE_POOL_2D":
            x = get(0)
            if _PAD_MODES[o["padding"]] == "SAME":
                y = _avg_pool_same_countvalid(
                    x, o["filter_h"], o["filter_w"],
                    o["stride_h"], o["stride_w"])
            else:
                y = lax.reduce_window(
                    x, 0.0, lax.add, (1, o["filter_h"], o["filter_w"], 1),
                    (1, o["stride_h"], o["stride_w"], 1), "VALID") \
                    / (o["filter_h"] * o["filter_w"])
            y = _fused_act(y, o["activation"])
        elif name == "MAX_POOL_2D":
            x = get(0)
            y = lax.reduce_window(
                x, -np.inf, lax.max, (1, o["filter_h"], o["filter_w"], 1),
                (1, o["stride_h"], o["stride_w"], 1),
                _PAD_MODES[o["padding"]])
            y = _fused_act(y, o["activation"])
        elif name in ("ADD", "MUL", "SUB", "DIV"):
            a, b = get(0), get(1)
            fn = {"ADD": jnp.add, "MUL": jnp.multiply,
                  "SUB": jnp.subtract, "DIV": jnp.divide}[name]
            y = _fused_act(fn(a, b), o.get("activation", 0))
        elif name in ("MAXIMUM", "MINIMUM"):
            y = (jnp.maximum if name == "MAXIMUM" else jnp.minimum)(
                get(0), get(1))
        elif name == "CONCATENATION":
            parts = [env[i] for i in op.inputs if i >= 0]
            y = _fused_act(jnp.concatenate(parts, axis=o["axis"]),
                           o.get("activation", 0))
        elif name == "RESHAPE":
            x = get(0)
            shape_t = get(1)
            if shape_t is not None:
                new_shape = [int(v) for v in np.asarray(shape_t)]
            else:
                new_shape = o.get("new_shape") or list(
                    self.sg.tensors[op.outputs[0]].shape)
            y = x.reshape(new_shape)
        elif name == "SQUEEZE":
            x = get(0)
            dims = o.get("squeeze_dims") or [
                i for i, d in enumerate(x.shape) if d == 1]
            y = x.reshape([d for i, d in enumerate(x.shape) if i not in
                           {d % x.ndim for d in dims}])
        elif name == "EXPAND_DIMS":
            x, ax = get(0), int(np.asarray(get(1)).reshape(()))
            y = jnp.expand_dims(x, ax)
        elif name == "SOFTMAX":
            import jax

            y = jax.nn.softmax(get(0) * np.float32(o.get("beta", 1.0)),
                               axis=-1)
        elif name == "LOGISTIC":
            import jax

            y = jax.nn.sigmoid(get(0))
        elif name == "TANH":
            y = jnp.tanh(get(0))
        elif name == "RELU":
            y = jnp.maximum(get(0), 0.0)
        elif name == "RELU6":
            y = jnp.clip(get(0), 0.0, 6.0)
        elif name == "PRELU":
            x, alpha = get(0), get(1)
            y = jnp.where(x >= 0, x, x * alpha)
        elif name == "LEAKY_RELU":
            x = get(0)
            y = jnp.where(x >= 0, x, x * np.float32(o.get("alpha", 0.0)))
        elif name == "HARD_SWISH":
            x = get(0)
            y = x * jnp.clip(x + 3.0, 0.0, 6.0) / 6.0
        elif name == "RESIZE_BILINEAR":
            x = get(0)
            size = np.asarray(get(1)).reshape(-1)
            y = _resize_bilinear(x, int(size[0]), int(size[1]),
                                 o.get("align_corners", False),
                                 o.get("half_pixel_centers", False))
        elif name == "RESIZE_NEAREST":
            x = get(0)
            size = np.asarray(get(1)).reshape(-1)
            oh, ow = int(size[0]), int(size[1])
            h, w = x.shape[1], x.shape[2]
            if o.get("half_pixel_centers"):
                iy = jnp.floor((jnp.arange(oh) + 0.5) * (h / oh))
                ix = jnp.floor((jnp.arange(ow) + 0.5) * (w / ow))
            elif o.get("align_corners") and oh > 1 and ow > 1:
                iy = jnp.round(jnp.arange(oh) * ((h - 1) / (oh - 1)))
                ix = jnp.round(jnp.arange(ow) * ((w - 1) / (ow - 1)))
            else:
                iy = (jnp.arange(oh) * h) // oh
                ix = (jnp.arange(ow) * w) // ow
            iy = jnp.clip(iy.astype(jnp.int32), 0, h - 1)
            ix = jnp.clip(ix.astype(jnp.int32), 0, w - 1)
            y = x[:, iy][:, :, ix]
        elif name in ("MEAN", "SUM", "REDUCE_MAX", "REDUCE_MIN"):
            x = get(0)
            axes = tuple(int(a) for a in np.asarray(get(1)).reshape(-1))
            red = {"MEAN": jnp.mean, "SUM": jnp.sum,
                   "REDUCE_MAX": jnp.max, "REDUCE_MIN": jnp.min}[name]
            y = red(x, axis=axes, keepdims=o.get("keep_dims", False))
        elif name in ("ARG_MAX", "ARG_MIN"):
            x = get(0)
            ax = int(np.asarray(get(1)).reshape(()))
            fn = jnp.argmax if name == "ARG_MAX" else jnp.argmin
            out_np = _TENSORTYPE_NP.get(o.get("output_type", 2), np.int32)
            y = fn(x, axis=ax).astype(out_np)
        elif name in ("PAD", "PAD_V2"):
            x, p = get(0), np.asarray(get(1))
            cval = 0.0
            if name == "PAD_V2" and get(2) is not None:
                cval = float(np.asarray(get(2)).reshape(()))
            y = jnp.pad(x, [(int(a), int(b)) for a, b in p],
                        constant_values=cval)
        elif name == "TRANSPOSE":
            x, perm = get(0), np.asarray(get(1)).reshape(-1)
            y = jnp.transpose(x, tuple(int(v) for v in perm))
        elif name == "FULLY_CONNECTED":
            x, w, b = get(0), get(1), get(2)
            x2 = x.reshape((-1, w.shape[-1])) if not o.get("keep_num_dims") \
                else x
            y = x2 @ w.T
            if b is not None:
                y = y + b
            y = _fused_act(y, o["activation"])
        elif name == "CAST":
            x = get(0)
            out_t = o.get("out_type")
            y = x.astype(self.sg.tensors[op.outputs[0]].np_dtype
                         if out_t is None
                         else _TENSORTYPE_NP.get(out_t, np.float32))
        elif name in ("DEQUANTIZE", "QUANTIZE"):
            # whole graph already runs dequantized float; both are identity
            # up to the requantize applied at graph outputs
            y = get(0)
        elif name == "SPACE_TO_DEPTH":
            x = get(0)
            bs = o["block_size"]
            n, h, w, c = x.shape
            y = x.reshape(n, h // bs, bs, w // bs, bs, c) \
                 .transpose(0, 1, 3, 2, 4, 5) \
                 .reshape(n, h // bs, w // bs, c * bs * bs)
        elif name == "DEPTH_TO_SPACE":
            x = get(0)
            bs = o["block_size"]
            n, h, w, c = x.shape
            y = x.reshape(n, h, w, bs, bs, c // (bs * bs)) \
                 .transpose(0, 1, 3, 2, 4, 5) \
                 .reshape(n, h * bs, w * bs, c // (bs * bs))
        elif name == "SHAPE":
            y = jnp.asarray(env[op.inputs[0]].shape, np.int32)
        elif name in ("LESS", "LESS_EQUAL", "GREATER", "GREATER_EQUAL",
                      "EQUAL", "NOT_EQUAL"):
            a, b = get(0), get(1)
            y = {"LESS": jnp.less, "LESS_EQUAL": jnp.less_equal,
                 "GREATER": jnp.greater, "GREATER_EQUAL": jnp.greater_equal,
                 "EQUAL": jnp.equal, "NOT_EQUAL": jnp.not_equal}[name](a, b)
        elif name in ("LOGICAL_AND", "LOGICAL_OR"):
            y = (jnp.logical_and if name == "LOGICAL_AND"
                 else jnp.logical_or)(get(0), get(1))
        elif name == "IF":
            # cond tensor + then/else subgraphs → lax.cond: both branches
            # trace (XLA requirement), matching output shapes enforced by
            # the schema (both subgraphs share the signature)
            import jax

            pred = jnp.reshape(get(0), ()).astype(bool)
            then_fn = self.root._subgraph_apply(o["then_subgraph"])
            else_fn = self.root._subgraph_apply(o["else_subgraph"])
            operands = tuple(env[i] for i in op.inputs[1:])
            live_params = env["__params__"]
            res = jax.lax.cond(
                pred,
                lambda args: tuple(then_fn(live_params, *args)),
                lambda args: tuple(else_fn(live_params, *args)),
                operands)
            for out_idx, val in zip(op.outputs, res):
                env[out_idx] = val
            return
        elif name == "WHILE":
            # cond/body subgraphs over a carried tuple → lax.while_loop
            # (shape/dtype-invariant carry — the compiler-friendly loop;
            # a shape-changing TFLite WHILE cannot map to XLA and errors)
            import jax

            cond_fn = self.root._subgraph_apply(o["cond_subgraph"])
            body_fn = self.root._subgraph_apply(o["body_subgraph"])
            carry0 = tuple(env[i] for i in op.inputs)
            live_params = env["__params__"]
            try:
                res = jax.lax.while_loop(
                    lambda c: jnp.reshape(
                        cond_fn(live_params, *c)[0], ()).astype(bool),
                    lambda c: tuple(body_fn(live_params, *c)),
                    carry0)
            except TypeError as e:
                raise NotImplementedError(
                    f"{os.path.basename(self.m.path)}: WHILE body changes "
                    f"carry shapes/dtypes — not expressible as an XLA "
                    f"while_loop ({e})") from e
            for out_idx, val in zip(op.outputs, res):
                env[out_idx] = val
            return
        elif name == "LOGICAL_NOT":
            y = jnp.logical_not(get(0))
        elif name == "LOG":
            y = jnp.log(get(0))
        elif name in ("SQRT", "RSQRT", "EXP", "ABS", "POW"):
            x = get(0)
            y = {"SQRT": jnp.sqrt, "RSQRT": lambda v: 1.0 / jnp.sqrt(v),
                 "EXP": jnp.exp, "ABS": jnp.abs}.get(name, None)
            y = y(x) if y is not None else jnp.power(x, get(1))
        elif name == "SLICE":
            x = get(0)
            begin = np.asarray(get(1)).reshape(-1)
            size = np.asarray(get(2)).reshape(-1)
            idx = tuple(slice(int(b), x.shape[i] if int(s) == -1
                              else int(b) + int(s))
                        for i, (b, s) in enumerate(zip(begin, size)))
            y = x[idx]
        elif name == "GATHER":
            x, indices = get(0), get(1)
            indices = jnp.asarray(indices).astype(jnp.int32)
            axis = o.get("axis", 0)
            bd = int(o.get("batch_dims", 0) or 0)
            if bd:
                # batched gather: vmap the plain take over the leading
                # batch dims shared by data and indices
                import jax

                if bd < 0:
                    bd += indices.ndim
                ax = axis if axis >= 0 else axis + x.ndim
                inner_ax = ax - bd
                fn = lambda a, i: jnp.take(a, i, axis=inner_ax)  # noqa: E731
                for _ in range(bd):
                    fn = jax.vmap(fn)
                y = fn(x, indices)
            else:
                y = jnp.take(x, indices, axis=axis)
        elif name == "PACK":
            y = jnp.stack([env[i] for i in op.inputs], axis=o.get("axis", 0))
        elif name == "STRIDED_SLICE":
            x = get(0)
            begin = np.asarray(get(1)).reshape(-1)
            end = np.asarray(get(2)).reshape(-1)
            strides = np.asarray(get(3)).reshape(-1) if get(3) is not None \
                else np.ones_like(begin)
            nspec = len(begin)
            new_mask = o.get("new_axis_mask", 0)
            ell_mask = o.get("ellipsis_mask", 0)
            if bin(ell_mask).count("1") > 1:
                raise ValueError("STRIDED_SLICE: multiple ellipsis bits")
            n_new = bin(new_mask & ((1 << nspec) - 1)).count("1")
            dims_covered = nspec - n_new - (1 if ell_mask else 0)
            ell_fill = x.ndim - dims_covered  # full slices the … expands to
            idx = []
            d = 0  # input dimension cursor (spec position i may diverge
            #        from it through new-axis and ellipsis entries)
            for i in range(nspec):
                if ell_mask & (1 << i):
                    for _ in range(max(ell_fill, 0)):
                        idx.append(slice(None))
                        d += 1
                    continue
                if new_mask & (1 << i):
                    idx.append(None)  # np.newaxis
                    continue
                dim = x.shape[d]
                b = int(begin[i])
                e = int(end[i])
                s = int(strides[i]) if i < len(strides) else 1
                # Start/StopForAxis semantics (strided_slice_logic.h):
                # masks and clamping resolve BEFORE shrink; the clamp
                # range is [0, dim] for positive stride and [-1, dim-1]
                # for negative (dim / -1 = "exhausted" → empty slice,
                # where -1 must NOT be handed to python slicing)
                if o.get("begin_mask", 0) & (1 << i):
                    b = 0 if s > 0 else dim - 1
                else:
                    if b < 0:
                        b += dim
                    if o.get("shrink_axis_mask", 0) & (1 << i):
                        b = int(np.clip(b, 0, dim - 1))
                    else:
                        b = int(np.clip(b, 0, dim)) if s > 0 \
                            else int(np.clip(b, -1, dim - 1))
                if o.get("shrink_axis_mask", 0) & (1 << i):
                    idx.append(b)
                    d += 1
                    continue
                if o.get("end_mask", 0) & (1 << i):
                    e = None
                else:
                    if e < 0:
                        e += dim
                    e = int(np.clip(e, 0, dim)) if s > 0 \
                        else int(np.clip(e, -1, dim - 1))
                if s < 0 and b == -1:
                    idx.append(slice(0, 0, 1))      # empty
                elif s < 0 and e == -1:
                    idx.append(slice(b, None, s))   # through index 0
                else:
                    idx.append(slice(b, e, s))
                d += 1
            while d < x.ndim:  # dims beyond the spec: full slices
                idx.append(slice(None))
                d += 1
            y = x[tuple(idx)]
        elif name == "TRANSPOSE_CONV":
            # inputs: 0 output_shape, 1 weights (OHWI, O=output ch),
            # 2 activations, 3 optional bias
            out_shape = np.asarray(get(0)).reshape(-1)
            w, x = get(1), get(2)
            b = get(3)
            # tflite transpose-conv == gradient of a conv: lax transposed
            # conv via conv_general_dilated with lhs_dilation = stride
            oh, ow = int(out_shape[1]), int(out_shape[2])
            sh, sw = o["stride_h"], o["stride_w"]
            kh, kw = w.shape[1], w.shape[2]
            # scatter semantics: out[y*s + fy - P] += x[y] * w[fy]. As a
            # conv: lhs_dilation = stride, kernel flipped spatially,
            # pad_low = k-1-P, pad_high chosen to land on out_shape
            # (dilated + pl + ph - k + 1 == out). VALID: P = 0.
            def pads(in_sz, out_sz, k, s, same):
                total = max((in_sz - 1) * s + k - out_sz, 0) if same else 0
                p = total // 2
                return (k - 1 - p, out_sz - (in_sz - 1) * s - 1 + p)

            same = _PAD_MODES[o["padding"]] == "SAME"
            pad_h = pads(x.shape[1], oh, kh, sh, same)
            pad_w = pads(x.shape[2], ow, kw, sw, same)
            # tflite transpose-conv weights are (out_ch, kh, kw, in_ch):
            # flip spatially, contract in_ch, emit out_ch → HWIO
            wt = jnp.transpose(w[:, ::-1, ::-1, :], (1, 2, 3, 0))
            y = lax.conv_general_dilated(
                x, wt, (1, 1), (pad_h, pad_w), lhs_dilation=(sh, sw),
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
            if b is not None:
                y = y + b
            y = _fused_act(y, o.get("activation", 0))
        elif name == "SPLIT":
            # inputs: 0 axis (scalar tensor), 1 x; N equal outputs
            # (the output COUNT is authoritative — it's what the graph
            # wires — and num_splits always equals it in valid models)
            ax = int(np.asarray(get(0)).reshape(()))
            x = get(1)
            parts = jnp.split(x, len(op.outputs), axis=ax)
            for out_idx, part in zip(op.outputs, parts):
                env[out_idx] = self._fake_quant(out_idx, part)
            return
        elif name == "UNPACK":
            x = get(0)
            ax = o.get("axis", 0)
            if o.get("num") and o["num"] != len(op.outputs):
                raise ValueError(
                    f"UNPACK num={o['num']} disagrees with "
                    f"{len(op.outputs)} wired outputs")
            for j, out_idx in enumerate(op.outputs):
                env[out_idx] = self._fake_quant(
                    out_idx, jnp.take(x, j, axis=ax))
            return
        elif name == "CUSTOM:TFLite_Detection_PostProcess":
            # SSD box-decode + NMS custom op (the graphs the reference's
            # mobilenet-ssd-postprocess decoder mode consumes,
            # tensordec-boundingbox.c:121-133). Same center-size decode +
            # greedy-NMS math as decoders/bounding_box.py, lowered into the
            # model's own XLA program. Both kernel paths: fast
            # (class-agnostic NMS over per-anchor best class) and regular
            # (per-class NMS, vmapped over classes).
            if int(o.get("max_classes_per_detection", 1)) != 1:
                raise NotImplementedError(
                    "TFLite_Detection_PostProcess: "
                    f"max_classes_per_detection="
                    f"{o.get('max_classes_per_detection')} is not supported "
                    "(only top-1 class per box)")
            import jax

            locs = get(0)[0]        # [N, 4] (y, x, h, w) encodings
            cls_in = get(1)[0]      # [N, C] scores (graph already applied
            #                         sigmoid/softmax before this op)
            anchors = get(2)        # [N, 4] (ycenter, xcenter, h, w)
            num_classes = int(o["num_classes"])
            max_d = int(o["max_detections"])
            label_offset = cls_in.shape[-1] - num_classes  # background cols
            cls_scores = cls_in[:, label_offset:]
            ya, xa, ha, wa = (anchors[:, 0], anchors[:, 1],
                              anchors[:, 2], anchors[:, 3])
            yc = locs[:, 0] / np.float32(o["y_scale"]) * ha + ya
            xc = locs[:, 1] / np.float32(o["x_scale"]) * wa + xa
            hh = jnp.exp(locs[:, 2] / np.float32(o["h_scale"])) * ha
            ww = jnp.exp(locs[:, 3] / np.float32(o["w_scale"])) * wa
            ymin, xmin = yc - hh / 2, xc - ww / 2
            ymax, xmax = yc + hh / 2, xc + ww / 2
            thr = np.float32(o.get("nms_score_threshold", 0.0))
            iou_thr = np.float32(o.get("nms_iou_threshold", 0.6))
            n = int(cls_scores.shape[0])
            # static pre-NMS candidate cap: the interpreter considers every
            # above-threshold anchor; 2048 covers the common SSD exports
            # (mobilenet-ssd = 1917 anchors). Beyond it, heavily-suppressed
            # scenes may backfill differently from rank >k — warn once.
            k = min(n, 2048)
            if n > k:
                from ..core.log import logger

                logger("tflite").warning(
                    "TFLite_Detection_PostProcess: %d anchors exceed the "
                    "%d pre-NMS candidate cap; detections may diverge from "
                    "the TFLite runtime when >%d candidates pass the score "
                    "threshold", n, k, k)
            neg_inf = np.float32(-np.inf)  # sentinel safe for logit-scale
            #                                thresholds (thr can be ≤ -1)

            def greedy_nms(scores_1d, cap):
                """Threshold → top-``cap`` → greedy same-order NMS.
                Returns (kept_scores[cap] with -inf for dead slots,
                anchor_idx[cap])."""
                masked = jnp.where(scores_1d >= thr, scores_1d, neg_inf)
                top_score, idx = jax.lax.top_k(masked, cap)
                by0, bx0 = ymin[idx], xmin[idx]
                by1, bx1 = ymax[idx], xmax[idx]
                area = (bx1 - bx0) * (by1 - by0)
                ix = (jnp.minimum(bx1[:, None], bx1[None, :])
                      - jnp.maximum(bx0[:, None], bx0[None, :]))
                iy = (jnp.minimum(by1[:, None], by1[None, :])
                      - jnp.maximum(by0[:, None], by0[None, :]))
                inter = jnp.clip(ix, 0) * jnp.clip(iy, 0)
                union = area[:, None] + area[None, :] - inter
                iou = jnp.where(union > 0, inter / union, 0.0)
                later = jnp.arange(cap)[None, :] > jnp.arange(cap)[:, None]
                suppresses = (iou > iou_thr) & later

                def body(i, alive):
                    return alive & ~(alive[i] & suppresses[i])

                alive = jax.lax.fori_loop(0, cap, body, top_score >= thr)
                return jnp.where(alive, top_score, neg_inf), idx

            if o.get("use_regular_nms"):
                # regular path: NMS runs per class (vmapped — the IoU
                # matrix is shared math, scores differ per class), each
                # class keeps top detections_per_class, then a global
                # top-max_detections ranks across classes
                dpc = int(o.get("detections_per_class", 100) or 100)
                # per-class candidate pool: the interpreter NMS-es every
                # above-threshold candidate; 2*dpc headroom lets suppressed
                # clusters backfill from lower ranks. Bounded so the
                # C×kc×kc IoU tensor stays small; warn when it binds.
                kc = min(k, max(2 * dpc, max_d, 128))
                if n > kc:
                    from ..core.log import logger

                    logger("tflite").warning(
                        "TFLite_Detection_PostProcess(regular): per-class "
                        "candidate pool capped at %d of %d anchors; heavy "
                        "same-class suppression may backfill differently "
                        "from the TFLite runtime", kc, n)
                kept_c, idx_c = jax.vmap(
                    lambda s: greedy_nms(s, kc))(cls_scores.T)  # [C, kc]
                if dpc < kc:
                    # zero out ranks beyond detections_per_class per class
                    rank = jnp.argsort(jnp.argsort(-kept_c, axis=1), axis=1)
                    kept_c = jnp.where(rank < dpc, kept_c, neg_inf)
                flat_scores = kept_c.reshape(-1)          # [C*kc]
                flat_anchor = idx_c.reshape(-1)
                flat_cls = jnp.repeat(
                    jnp.arange(num_classes, dtype=jnp.float32), kc)
                final_score, fsel = jax.lax.top_k(
                    flat_scores, min(max_d, int(flat_scores.shape[0])))
                sel = flat_anchor[fsel]
                sel_cls = flat_cls[fsel]
            else:
                # fast path: class-agnostic NMS over per-anchor best class
                best_score = jnp.max(cls_scores, axis=1)
                best_cls = jnp.argmax(cls_scores, axis=1)
                kept, idx = greedy_nms(best_score, k)
                final_score, fsel = jax.lax.top_k(kept, min(max_d, k))
                sel = idx[fsel]
                sel_cls = best_cls[sel].astype(jnp.float32)
            pad = max_d - int(final_score.shape[0])
            valid = final_score >= thr
            out_boxes = jnp.where(
                valid[:, None],
                jnp.stack([ymin[sel], xmin[sel], ymax[sel], xmax[sel]], 1),
                0.0)
            out_cls = jnp.where(valid, sel_cls, 0.0)
            out_scr = jnp.where(valid, final_score, 0.0)
            if pad:
                out_boxes = jnp.pad(out_boxes, ((0, pad), (0, 0)))
                out_cls = jnp.pad(out_cls, (0, pad))
                out_scr = jnp.pad(out_scr, (0, pad))
            num = jnp.sum(valid.astype(jnp.float32))[None]
            for out_idx, val in zip(op.outputs, (
                    out_boxes[None], out_cls[None], out_scr[None], num)):
                env[out_idx] = val
            return
        else:
            raise NotImplementedError(
                f"{os.path.basename(self.m.path)}: TFLite op {name!r} is "
                "not in the supported lowering subset")
        outs = op.outputs
        env[outs[0]] = self._fake_quant(outs[0], y)
        if len(outs) > 1:
            raise NotImplementedError(f"multi-output op {name}")

    def _fake_quant(self, tensor_idx: int, y):
        """Snap an op result onto its output tensor's quantization grid.

        In a quantized graph the activation clamp is ENCODED IN THE QUANT
        RANGE (e.g. relu6 = range [0, 6] with zero_point 0), not in the
        fused_activation_function field — float execution must therefore
        round-and-clamp every intermediate to its tensor's representable
        grid or activations blow past their trained ranges and saturate
        the final requantize. Pure elementwise math; XLA fuses it into the
        producing op."""
        import jax.numpy as jnp

        t = self.sg.tensors[tensor_idx]
        if t.quant is None or np.issubdtype(np.dtype(t.np_dtype),
                                            np.floating):
            return y
        if t.quant.per_channel or not np.issubdtype(y.dtype, np.floating):
            return y  # per-channel activations don't occur in practice
        info = np.iinfo(t.np_dtype)
        scale = np.float32(t.quant.scale)
        zp = np.float32(t.quant.zero_point)
        q = jnp.clip(jnp.round(y / scale + zp), info.min, info.max)
        return (q - zp) * scale


# --------------------------------------------------------------------------- #
# Public entry: .tflite path → ModelBundle
# --------------------------------------------------------------------------- #


def _tensor_info(t: TFLTensor) -> TensorInfo:
    shape = t.shape if t.shape else (1,)
    return TensorInfo.from_shape(shape, np.dtype(t.np_dtype), t.name)


def load_tflite(path: str) -> ModelBundle:
    """``model=foo.tflite`` → ModelBundle (apply + params + I/O info).

    The bundle's I/O contract mirrors the flatbuffer exactly (dims, dtype —
    incl. uint8 for quantized models), so caps negotiation produces the
    same ``other/tensor`` caps the reference's tflite subplugin reports
    via ``getModelInfo`` (tensor_filter_tensorflow_lite.cc)."""
    m = parse_tflite(path)
    # every guarded corner must surface HERE: load_tflite(path) is the
    # documented one-line compatibility test (migrating-from-nnstreamer.md)
    for role, idxs in (("input", m.inputs), ("output", m.outputs)):
        for i in idxs:
            t = m.tensors[i]
            if not np.issubdtype(np.dtype(t.np_dtype), np.floating):
                _require_per_tensor_io(m, t, role)
    # op inventory spans EVERY subgraph (IF/WHILE bodies included), and
    # unknown opcodes fail at load, not at first inference
    all_ops: set = set()
    for sgi in (m.subgraphs or [m]):
        all_ops.update(op.op for op in sgi.operators)
    bad = sorted(n for n in all_ops
                 if n.startswith(("UNKNOWN_", "BADCODE_"))
                 or (n.startswith("CUSTOM:") and n not in _SUPPORTED_CUSTOM))
    if bad:
        raise NotImplementedError(
            f"{os.path.basename(path)}: unsupported op(s) {', '.join(bad)}")
    ops_used = sorted(all_ops)
    low = _Lowerer(m)
    apply = low.build_apply()
    in_info = TensorsInfo(tuple(_tensor_info(m.tensors[i]) for i in m.inputs))
    out_info = TensorsInfo(tuple(_tensor_info(m.tensors[i])
                                 for i in m.outputs))
    log.info("tflite import %s: %d ops (%s), %d params",
             os.path.basename(path), len(m.operators), ",".join(ops_used),
             len(low.params))
    return ModelBundle(
        os.path.basename(path), apply, params=low.params,
        in_info=in_info, out_info=out_info,
        metadata={"deployed_from": path, "format": "tflite",
                  "tflite_ops": ops_used,
                  "tflite_version": m.version})
