"""PoseNet keypoint estimation — BASELINE config 4.

Native flax stand-in for the reference's posenet tflite pipeline
(tests/nnstreamer_decoder_pose + tensordec-pose.c heatmap-offset mode):
MobileNet-v2 backbone → heatmaps [K:W':H':1] + offsets [2K:W':H':1], the
tensor pair the pose decoder consumes.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..core.types import TensorsInfo
from .mobilenet_v2 import ConvBNReLU, InvertedResidual, _make_divisible, preprocess_uint8
from .zoo import ModelBundle, register_model


class PoseNet(nn.Module):
    num_keypoints: int = 17
    width: float = 1.0
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        w = self.width
        x = ConvBNReLU(_make_divisible(32 * w), stride=2, dtype=self.dtype)(x, train)
        cfg = [(1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
               (6, 96, 3, 1)]
        for t, c, n, s in cfg:
            for i in range(n):
                x = InvertedResidual(_make_divisible(c * w), s if i == 0 else 1,
                                     t, dtype=self.dtype)(x, train)
        heat = nn.Conv(self.num_keypoints, (1, 1), dtype=self.dtype,
                       name="heatmap_head")(x)
        offs = nn.Conv(2 * self.num_keypoints, (1, 1), dtype=self.dtype,
                       name="offset_head")(x)
        return heat.astype(jnp.float32), offs.astype(jnp.float32)


def make_posenet(width: str = "1.0", size: str = "257",
                 num_keypoints: str = "17", seed: str = "0",
                 batch: str = "1", dtype: str = "bfloat16",
                 **_: Any) -> ModelBundle:
    w, hw, k, b = float(width), int(size), int(num_keypoints), int(batch)
    model = PoseNet(num_keypoints=k, width=w,
                    dtype=jnp.bfloat16 if dtype == "bfloat16" else jnp.float32)
    from .zoo import init_variables

    variables = init_variables(model, int(seed),
                               jnp.zeros((b, hw, hw, 3), jnp.float32))
    out_hw = -(-hw // 16)  # stride-16 feature grid

    def apply(params, x):
        if x.dtype == jnp.uint8:
            x = preprocess_uint8(x)
        return model.apply(params, x, train=False)

    return ModelBundle(
        "posenet", apply, params=variables,
        in_info=TensorsInfo.from_strings(f"3:{hw}:{hw}:{b}", "uint8"),
        out_info=TensorsInfo.from_strings(
            f"{k}:{out_hw}:{out_hw}:{b},{2 * k}:{out_hw}:{out_hw}:{b}",
            "float32,float32"),
        preprocess=preprocess_uint8,
        metadata={"keypoints": k, "size": hw, "grid": out_hw})


register_model("posenet", make_posenet)
