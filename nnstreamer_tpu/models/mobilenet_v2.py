"""MobileNet-v2 in flax — the flagship streaming-classification model.

Fills the role of the reference's mobilenet tflite models
(tests/test_models/models/mobilenet_v*; BASELINE config "MobileNet-v2
image_labeling") as a native JAX/flax implementation designed for the MXU:
NHWC layout, channels padded to hardware-friendly multiples via the width
multiplier, bf16 compute with f32 params by default.

Output is 1001-way logits (background class + 1000 ImageNet classes), the
tflite convention the reference's image_labeling decoder expects.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..core.types import TensorsInfo
from .zoo import ModelBundle, register_model

# (expansion t, out channels c, repeats n, stride s) — MobileNet-v2 paper table 2
_INVERTED_RESIDUAL_SETTINGS: Sequence[Tuple[int, int, int, int]] = (
    (1, 16, 1, 1),
    (6, 24, 2, 2),
    (6, 32, 3, 2),
    (6, 64, 4, 2),
    (6, 96, 3, 1),
    (6, 160, 3, 2),
    (6, 320, 1, 1),
)


def _make_divisible(v: float, divisor: int = 8) -> int:
    new_v = max(divisor, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class ConvBNReLU(nn.Module):
    features: int
    kernel: int = 3
    stride: int = 1
    groups: int = 1
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Conv(self.features, (self.kernel, self.kernel),
                    strides=self.stride, padding="SAME",
                    feature_group_count=self.groups, use_bias=False,
                    dtype=self.dtype)(x)
        x = nn.BatchNorm(use_running_average=not train, dtype=self.dtype,
                         momentum=0.97, epsilon=1e-3)(x)
        return jnp.minimum(jnp.maximum(x, 0.0), 6.0)  # ReLU6


class InvertedResidual(nn.Module):
    features: int
    stride: int
    expand_ratio: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        in_ch = x.shape[-1]
        hidden = in_ch * self.expand_ratio
        use_res = self.stride == 1 and in_ch == self.features
        y = x
        if self.expand_ratio != 1:
            y = ConvBNReLU(hidden, kernel=1, dtype=self.dtype)(y, train)
        # depthwise
        y = ConvBNReLU(hidden, kernel=3, stride=self.stride, groups=hidden,
                       dtype=self.dtype)(y, train)
        # linear projection
        y = nn.Conv(self.features, (1, 1), use_bias=False, dtype=self.dtype)(y)
        y = nn.BatchNorm(use_running_average=not train, dtype=self.dtype,
                         momentum=0.97, epsilon=1e-3)(y)
        return x + y if use_res else y


class MobileNetV2(nn.Module):
    num_classes: int = 1001
    width: float = 1.0
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        ch = _make_divisible(32 * self.width)
        x = ConvBNReLU(ch, stride=2, dtype=self.dtype)(x, train)
        for t, c, n, s in _INVERTED_RESIDUAL_SETTINGS:
            out_ch = _make_divisible(c * self.width)
            for i in range(n):
                x = InvertedResidual(out_ch, s if i == 0 else 1, t,
                                     dtype=self.dtype)(x, train)
        last = _make_divisible(1280 * max(1.0, self.width))
        x = ConvBNReLU(last, kernel=1, dtype=self.dtype)(x, train)
        x = jnp.mean(x, axis=(1, 2))  # global average pool
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)


def preprocess_uint8(x: jax.Array) -> jax.Array:
    """uint8 RGB [0,255] → float [-1,1] (tflite mobilenet convention)."""
    return x.astype(jnp.float32) / 127.5 - 1.0


def make_mobilenet_bundle(name: str, model_cls: Any, width: str = "1.0",
                          size: str = "224", num_classes: str = "1001",
                          checkpoint: Optional[str] = None,
                          dtype: str = "bfloat16", seed: str = "0",
                          batch: str = "1", **_: Any) -> ModelBundle:
    """Shared classifier-bundle factory: the serving contract (uint8
    preprocessing dispatch, checkpoint restore, I/O metadata) is ONE
    definition for every mobilenet-family class (v1/v2)."""
    w, hw, nc, b = float(width), int(size), int(num_classes), int(batch)
    model = model_cls(num_classes=nc, width=w,
                      dtype=jnp.bfloat16 if dtype == "bfloat16" else jnp.float32)
    from .zoo import init_variables

    variables = init_variables(model, int(seed),
                               jnp.zeros((b, hw, hw, 3), jnp.float32))
    if checkpoint:
        from ..utils import checkpoints

        variables = checkpoints.load_variables(checkpoint, variables)

    def apply(params, x):
        if x.dtype == jnp.uint8:
            x = preprocess_uint8(x)
        return model.apply(params, x, train=False)

    in_info = TensorsInfo.from_strings(f"3:{hw}:{hw}:{b}", "uint8")
    out_info = TensorsInfo.from_strings(f"{nc}:{b}", "float32")
    return ModelBundle(name, apply, params=variables,
                       in_info=in_info, out_info=out_info,
                       preprocess=preprocess_uint8,
                       metadata={"width": w, "size": hw, "classes": nc})


def make_mobilenet_v2(**options: Any) -> ModelBundle:
    return make_mobilenet_bundle("mobilenet_v2", MobileNetV2, **options)


register_model("mobilenet_v2", make_mobilenet_v2)
