"""Loader for the legacy (torch-1.0 era) TorchScript zip format.

Modern ``torch.jit.load`` rejects these files ("Legacy model format is not
supported"), but the format is fully self-describing: the zip carries
``model.json`` (protoVersion 2 — module tree, parameter metadata, raw tensor
blobs) and the TorchScript source for each module's ``forward`` under
``code/``.  The arena source is generated from a *restricted* serializer (no
classes, no imports, a small fixed op vocabulary), so instead of a TorchScript
frontend we execute it directly as Python against a shim ``torch`` namespace
that maps the era's internal ops (``_cast_Float``, ``_convolution``,
``transpose_``, ``ops.prim.NumToTensor`` …) onto modern equivalents.

This serves the reference's own ``pytorch_lenet5.pt`` asset unmodified —
the file its pytorch filter test uses (reference:
tests/nnstreamer_filter_pytorch/runTest.sh:72, served by
ext/nnstreamer/tensor_filter/tensor_filter_pytorch.cc).
"""

from __future__ import annotations

import json
import types
import zipfile
from typing import Any, Dict, List, Optional

import numpy as np

__all__ = ["is_legacy_torchscript", "load_legacy_torchscript", "LegacyTorchScriptError"]


class LegacyTorchScriptError(RuntimeError):
    """A legacy-format file was recognised but could not be executed."""


#: model.json dataType → numpy dtype (legacy caffe2-style names)
_DTYPES = {
    "FLOAT": np.float32,
    "DOUBLE": np.float64,
    "FLOAT16": np.float16,
    "INT64": np.int64,
    "INT32": np.int32,
    "INT16": np.int16,
    "INT8": np.int8,
    "UINT8": np.uint8,
    "BOOL": np.bool_,
}


def is_legacy_torchscript(path: str) -> bool:
    """True iff *path* is a legacy TorchScript zip (contains ``*/model.json``).

    Modern TorchScript zips carry ``data.pkl`` + ``constants.pkl`` instead.
    """
    try:
        if not zipfile.is_zipfile(path):
            return False
        with zipfile.ZipFile(path) as z:
            names = z.namelist()
            # a modern archive can carry _extra_files entries named
            # model.json (stored under <root>/extra/); data.pkl is the
            # authoritative modern marker, root-level model.json the legacy one
            if any(n.split("/")[-1] == "data.pkl" for n in names):
                return False
            return any(n.count("/") == 1 and n.endswith("/model.json")
                       for n in names)
    except (OSError, zipfile.BadZipFile):
        return False


class _TorchShim:
    """``torch`` namespace seen by legacy arena code.

    Unknown attributes fall through to real torch; only renamed/removed
    era-internal ops are overridden.
    """

    def __getattr__(self, name: str) -> Any:
        import torch

        return getattr(torch, name)

    # -- casts ---------------------------------------------------------
    @staticmethod
    def _cast_Float(x, non_blocking=False):
        return x.float()

    @staticmethod
    def _cast_Double(x, non_blocking=False):
        return x.double()

    @staticmethod
    def _cast_Byte(x, non_blocking=False):
        import torch

        return x.to(torch.uint8)

    @staticmethod
    def _cast_Char(x, non_blocking=False):
        import torch

        return x.to(torch.int8)

    @staticmethod
    def _cast_Int(x, non_blocking=False):
        import torch

        return x.to(torch.int32)

    @staticmethod
    def _cast_Long(x, non_blocking=False):
        import torch

        return x.to(torch.int64)

    # -- renamed / method-only ops ------------------------------------
    @staticmethod
    def transpose_(x, a, b):
        # functional is fine: legacy codegen never aliases the input again
        return x.transpose(a, b)

    @staticmethod
    def view(x, shape):
        return x.reshape(shape)

    @staticmethod
    def size(x, dim=None):
        return x.size() if dim is None else x.size(dim)

    @staticmethod
    def dim(x):
        return x.dim()

    @staticmethod
    def t(x):
        return x.t()

    @staticmethod
    def contiguous(x):
        return x.contiguous()

    @staticmethod
    def _convolution(inp, weight, bias, stride, padding, dilation, transposed,
                     output_padding, groups, *flags):
        """Era signature of aten::_convolution (12 args; modern added more
        trailing bools — absorbed by *flags)."""
        import torch.nn.functional as F

        nd = weight.dim() - 2
        if transposed:
            fn = (F.conv_transpose1d, F.conv_transpose2d, F.conv_transpose3d)[nd - 1]
            return fn(inp, weight, bias, stride, padding, output_padding, groups, dilation)
        fn = (F.conv1d, F.conv2d, F.conv3d)[nd - 1]
        return fn(inp, weight, bias, stride, padding, dilation, groups)

    @staticmethod
    def warn(*args, **kwargs):  # torch.warn(msg, stacklevel=) — codegen chatter
        return None

    @staticmethod
    def format(fmt, *args):  # torch.format("... {}", x) → str.format
        return fmt.format(*args)

    # -- identity / comparison intrinsics ------------------------------
    @staticmethod
    def __is__(a, b):
        return a is b

    @staticmethod
    def __isnot__(a, b):
        return a is not b

    @staticmethod
    def __not__(a):
        return not a


class _PrimOps:
    @staticmethod
    def NumToTensor(n):
        import torch

        return torch.tensor(n)

    @staticmethod
    def unchecked_unwrap_optional(x):
        return x

    @staticmethod
    def TupleConstruct(*xs):
        return tuple(xs)

    @staticmethod
    def min(*xs):
        return min(xs) if len(xs) > 1 else min(xs[0])


class _AtenOps:
    def __getattr__(self, name: str) -> Any:
        import torch

        return getattr(torch, name)


class _Ops:
    prim = _PrimOps()
    aten = _AtenOps()


class _LegacyModule:
    """A node of the deserialized module tree (params + submodules + forward)."""

    def __init__(self, name: str) -> None:
        self._name = name

    def __call__(self, *args: Any) -> Any:
        return self.forward(*args)

    def forward(self, *args: Any) -> Any:  # replaced per-module when an arena exists
        raise LegacyTorchScriptError(
            f"legacy module {self._name!r} has no torchscript arena")

    # torch.nn.Module API surface the filter touches
    def eval(self) -> "_LegacyModule":
        return self

    def to(self, *a: Any, **k: Any) -> "_LegacyModule":
        return self

    def __repr__(self) -> str:
        return f"<LegacyScriptModule {self._name!r}>"


def _read_tensors(z: zipfile.ZipFile, root: str, j: Dict[str, Any]) -> List[Any]:
    import torch

    out = []
    for t in j.get("tensors", []):
        np_dt = _DTYPES.get(t["dataType"])
        if np_dt is None:
            raise LegacyTorchScriptError(f"unsupported tensor dataType {t['dataType']!r}")
        dims = [int(d) for d in t.get("dims", [])]
        strides = [int(s) for s in t.get("strides", [])]
        # a strided view can span more storage than prod(dims) elements
        if dims and strides:
            count = 1 + sum((d - 1) * s for d, s in zip(dims, strides))
        else:
            count = int(np.prod(dims)) if dims else 1
        raw = z.read(root + t["data"]["key"])
        offset = int(t.get("offset", 0)) * np.dtype(np_dt).itemsize
        arr = np.frombuffer(raw, dtype=np_dt, count=count, offset=offset)
        if dims and strides and strides != _contig_strides(dims):
            arr = np.lib.stride_tricks.as_strided(
                arr, shape=dims,
                strides=[s * arr.itemsize for s in strides]).copy()
        else:
            arr = arr[: int(np.prod(dims)) if dims else 1].reshape(dims).copy()
        out.append(torch.from_numpy(arr))
    return out


def _contig_strides(dims: List[int]) -> List[int]:
    st, acc = [], 1
    for d in reversed(dims):
        st.append(acc)
        acc *= d
    return list(reversed(st))


import builtins as _builtins

#: module roots torch's own dispatch machinery may pull in from the calling
#: frame's builtins (torch.threshold etc. resolve overloads via __import__)
_ALLOWED_IMPORT_ROOTS = frozenset(
    {"torch", "typing", "math", "numbers", "warnings", "collections",
     "functools", "itertools", "operator"})


def _guarded_import(name, globals=None, locals=None, fromlist=(), level=0):
    if name.split(".")[0] not in _ALLOWED_IMPORT_ROOTS:
        raise LegacyTorchScriptError(
            f"legacy arena attempted to import {name!r}")
    return _builtins.__import__(name, globals, locals, fromlist, level)


#: the only builtins era-generated arena code uses. NOTE: this is NOT a
#: security boundary — arena code is still Python and attribute traversal
#: can reach anything (same trust model as torch.jit.load/pickle: model
#: files are code). The guard exists to fail fast on accidental non-arena
#: content, not to contain a hostile file.
_ARENA_BUILTINS = {
    n: getattr(_builtins, n)
    for n in ("int", "float", "bool", "str", "len", "min", "max", "abs",
              "range", "enumerate", "zip", "tuple", "list", "isinstance",
              "getattr", "setattr", "print")
}
_ARENA_BUILTINS["__import__"] = _guarded_import


def _arena_globals() -> Dict[str, Any]:
    import torch

    return {
        "torch": _TorchShim(),
        "ops": _Ops(),
        "annotate": lambda _ty, v: v,
        "unchecked_cast": lambda _ty, v: v,
        "uninitialized": lambda _ty: None,
        "Tensor": torch.Tensor,
        "Optional": Optional,
        "List": List,
        "Dict": Dict,
        "op_version_set": 0,
        "__builtins__": _ARENA_BUILTINS,
    }


def _build_module(z: zipfile.ZipFile, root: str, mdef: Dict[str, Any],
                  tensors: List[Any]) -> _LegacyModule:
    mod = _LegacyModule(mdef.get("name", "<main>"))
    for p in mdef.get("parameters", []):
        setattr(mod, p["name"], tensors[int(p["tensorId"])])
    for s in mdef.get("submodules", []):
        setattr(mod, s["name"], _build_module(z, root, s, tensors))
    arena = mdef.get("torchscriptArena")
    if arena:
        src = z.read(root + arena["key"]).decode("utf-8")
        g = _arena_globals()
        prelude = set(g)
        try:
            exec(compile(src, arena["key"], "exec"), g)  # noqa: S102 — limited-builtins namespace
        except Exception as e:  # pragma: no cover - defensive
            raise LegacyTorchScriptError(
                f"failed to execute legacy arena {arena['key']!r}: {e}") from e
        # bind only names the arena itself defined (not the prelude lambdas)
        for name in set(g) - prelude:
            fn = g[name]
            if isinstance(fn, types.FunctionType):
                setattr(mod, name, types.MethodType(fn, mod))
    return mod


def load_legacy_torchscript(path: str) -> _LegacyModule:
    """Deserialize a legacy TorchScript zip into a callable module tree."""
    with zipfile.ZipFile(path) as z:
        json_name = next(
            (n for n in z.namelist() if n.split("/")[-1] == "model.json"), None)
        if json_name is None:
            raise LegacyTorchScriptError(f"{path}: no model.json — not legacy format")
        root = json_name[: -len("model.json")]
        j = json.loads(z.read(json_name))
        if str(j.get("protoVersion")) not in ("1", "2"):
            raise LegacyTorchScriptError(
                f"{path}: unsupported legacy protoVersion {j.get('protoVersion')!r}")
        tensors = _read_tensors(z, root, j)
        return _build_module(z, root, j["mainModule"], tensors)
