"""Model zoo: native JAX/flax models + the ModelBundle contract."""

from .zoo import ModelBundle, get_model, model_names, register_model

__all__ = ["ModelBundle", "get_model", "model_names", "register_model"]
