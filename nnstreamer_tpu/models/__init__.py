"""Model zoo: native JAX/flax models + the ModelBundle contract."""

from .deploy import export_model, load_checkpointed, load_exported
from .zoo import ModelBundle, get_model, model_names, register_model

__all__ = ["ModelBundle", "export_model", "get_model", "load_checkpointed",
           "load_exported", "model_names", "register_model"]
