"""Model zoo registry + the ModelBundle contract used by the xla-tpu backend.

The reference loads opaque model files (.tflite/.pb/.pt) through per-backend
C++ runtimes; the TPU-native equivalent is a *pure function + params* pair
compiled by XLA. ``ModelBundle`` is that contract. Sources:

 * zoo models registered here ("zoo://mobilenet_v2?width=0.25"),
 * user .py files exporting ``make_model(options) -> ModelBundle`` (or dict),
 * in-process callables / flax modules handed directly to ``model=``.

Params checkpointing uses orbax/flax serialization; a bundle may lazily
initialize random params when no checkpoint is given (streaming smoke tests
and benchmarks exercise compute, not trained weights — like the reference's
tests use tiny stand-in models, component-description.md:126).
"""

from __future__ import annotations

import threading
import urllib.parse
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ..core.types import TensorsInfo

_lock = threading.Lock()
_factories: Dict[str, Callable[..., "ModelBundle"]] = {}


@dataclass
class ModelBundle:
    """A jax-callable model: ``apply(params, *inputs) -> output(s)``.

    ``in_info``/``out_info`` describe per-frame I/O (batch dim included).
    ``preprocess``/``postprocess`` are optional jax-traceable stages the
    pipeline may fuse into the same XLA program as the model.
    """

    name: str
    apply: Callable[..., Any]
    params: Any = None
    in_info: Optional[TensorsInfo] = None
    out_info: Optional[TensorsInfo] = None
    preprocess: Optional[Callable[..., Any]] = None
    postprocess: Optional[Callable[..., Any]] = None
    metadata: Dict[str, Any] = field(default_factory=dict)

    def fn(self) -> Callable[..., Any]:
        """Params-closed pure function over input arrays."""
        params = self.params
        apply = self.apply
        if params is None:
            return apply
        return lambda *xs: apply(params, *xs)


def init_variables(module: Any, seed: int, *dummies: Any) -> Any:
    """Fast zoo-model initialization.

    On CPU this is flax's exact ``init`` compiled into ONE XLA program
    (eager init is hundreds of tiny dispatches).  On an accelerator —
    especially a high-RTT TPU tunnel where even the init *compile* costs
    minutes — the param pytree comes from ``jax.eval_shape`` (a pure
    trace: zero device ops) and the values are synthesized host-side with
    flax-like statistics (lecun-normal kernels, ones for scales/vars,
    zeros for biases/means).  Zoo weights are untrained placeholders
    either way; checkpoints (``custom="arch=..."``) replace them for real
    serving, so value-level init fidelity is not load-bearing while init
    latency very much is.
    """
    import jax

    key = jax.random.PRNGKey(int(seed))
    if jax.default_backend() == "cpu":
        return jax.jit(lambda k: module.init(k, *dummies))(key)
    shapes = jax.eval_shape(lambda k: module.init(k, *dummies), key)
    return synthesize_variables(shapes, int(seed))


def synthesize_variables(shape_tree: Any, seed: int) -> Any:
    """ShapeDtypeStruct pytree → numpy params with flax-like statistics,
    deterministically from ``seed`` (host-side; no device ops)."""
    import jax
    import numpy as np

    rng = np.random.default_rng(seed)
    leaves_with_paths, treedef = jax.tree_util.tree_flatten_with_path(
        shape_tree)
    out = []
    for path, leaf in leaves_with_paths:
        shape = tuple(leaf.shape)
        dtype = np.dtype(leaf.dtype)
        name = ""
        for p in reversed(path):
            key_attr = getattr(p, "key", None) or getattr(p, "name", None)
            if isinstance(key_attr, str):
                name = key_attr.lower()
                break
        if "kernel" in name or "embedding" in name:
            fan_in = int(np.prod(shape[:-1])) if len(shape) > 1 else \
                max(shape[0] if shape else 1, 1)
            arr = rng.normal(0.0, 1.0 / np.sqrt(max(fan_in, 1)),
                             shape).astype(dtype)
        elif "scale" in name or "var" in name:
            arr = np.ones(shape, dtype)
        elif "bias" in name or "mean" in name or len(shape) < 2 or \
                not np.issubdtype(dtype, np.floating):
            arr = np.zeros(shape, dtype)
        else:
            # unrecognized matrix-like float leaf (e.g. MoE router/w1/w2,
            # pos_embed): fan-in normal — zeros here would silently turn
            # whole layers into no-ops on accelerator-backend init
            fan_in = int(np.prod(shape[:-1]))
            arr = rng.normal(0.0, 1.0 / np.sqrt(max(fan_in, 1)),
                             shape).astype(dtype)
        out.append(arr)
    return jax.tree_util.tree_unflatten(treedef, out)


_aliases: Dict[str, str] = {}


def register_model(name: str, factory: Callable[..., ModelBundle]) -> None:
    """Register a zoo factory. A direct registration always wins: it drops
    any alias previously installed under the same name (user factories must
    never be silently shadowed by built-in aliases)."""
    with _lock:
        _factories[name.lower()] = factory
        _aliases.pop(name.lower(), None)


def register_alias(alias: str, canonical: str) -> None:
    """Map ``alias`` onto an existing canonical model name so both resolve
    to the same memoized bundle (one compile). The target is validated
    eagerly; a direct factory under ``alias`` keeps precedence."""
    with _lock:
        target = _aliases.get(canonical.lower(), canonical.lower())
        if target not in _factories:
            raise ValueError(
                f"register_alias: unknown canonical model {canonical!r}")
        _aliases[alias.lower()] = target


def model_names() -> List[str]:
    _ensure_builtin_models()
    with _lock:
        return sorted(set(_factories) | set(_aliases))


#: resolved-bundle memo: repeated ``zoo://`` specs (e.g. a latency and a
#: throughput pipeline over the same model) share one bundle — and through
#: the filter's jit cache, ONE compile. Skipped when an option references a
#: filesystem path (checkpoints may change between loads).
_bundle_memo: Dict[Any, ModelBundle] = {}


def get_model(spec: str, **overrides: Any) -> ModelBundle:
    """Resolve "zoo://name?opt=val" or bare "name"."""
    import os

    _ensure_builtin_models()
    s = spec
    if s.startswith("zoo://"):
        s = s[len("zoo://"):]
    if "?" in s:
        s, qs = s.split("?", 1)
        opts = {k: v[0] for k, v in urllib.parse.parse_qs(qs).items()}
    else:
        opts = {}
    opts.update(overrides)
    with _lock:
        s = s.lower()
        if s not in _factories:  # direct registrations beat aliases
            s = _aliases.get(s, s)
        factory = _factories.get(s)
    if factory is None:
        raise ValueError(f"unknown zoo model {spec!r}; known: {model_names()}")
    cacheable = all(isinstance(v, str) and not os.path.exists(v)
                    for v in opts.values())
    key = (s, tuple(sorted(opts.items()))) if cacheable else None
    if key is not None:
        with _lock:
            hit = _bundle_memo.get(key)
        if hit is not None:
            return hit
    bundle = factory(**opts)
    if key is not None:
        with _lock:
            if len(_bundle_memo) > 64:
                _bundle_memo.clear()
            _bundle_memo[key] = bundle
    return bundle


_builtins_loaded = False


def _ensure_builtin_models() -> None:
    # NOTE: flag is set AFTER the imports: a failing builtin module must
    # surface its ImportError on every call, not leave an empty catalog
    global _builtins_loaded
    if _builtins_loaded:
        return
    from . import mobilenet_v2  # noqa: F401
    from . import mobilenet_v1  # noqa: F401
    from . import simple  # noqa: F401
    from . import ssd_mobilenet  # noqa: F401
    from . import deeplab  # noqa: F401
    from . import posenet  # noqa: F401
    from . import lstm  # noqa: F401
    from . import lenet  # noqa: F401
    from . import stream_transformer  # noqa: F401
    from . import moe_transformer  # noqa: F401
    from . import causal_lm  # noqa: F401
    _builtins_loaded = True
