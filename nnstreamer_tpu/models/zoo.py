"""Model zoo registry + the ModelBundle contract used by the xla-tpu backend.

The reference loads opaque model files (.tflite/.pb/.pt) through per-backend
C++ runtimes; the TPU-native equivalent is a *pure function + params* pair
compiled by XLA. ``ModelBundle`` is that contract. Sources:

 * zoo models registered here ("zoo://mobilenet_v2?width=0.25"),
 * user .py files exporting ``make_model(options) -> ModelBundle`` (or dict),
 * in-process callables / flax modules handed directly to ``model=``.

Params checkpointing uses orbax/flax serialization; a bundle may lazily
initialize random params when no checkpoint is given (streaming smoke tests
and benchmarks exercise compute, not trained weights — like the reference's
tests use tiny stand-in models, component-description.md:126).
"""

from __future__ import annotations

import threading
import urllib.parse
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ..core.types import TensorsInfo

_lock = threading.Lock()
_factories: Dict[str, Callable[..., "ModelBundle"]] = {}


@dataclass
class ModelBundle:
    """A jax-callable model: ``apply(params, *inputs) -> output(s)``.

    ``in_info``/``out_info`` describe per-frame I/O (batch dim included).
    ``preprocess``/``postprocess`` are optional jax-traceable stages the
    pipeline may fuse into the same XLA program as the model.
    """

    name: str
    apply: Callable[..., Any]
    params: Any = None
    in_info: Optional[TensorsInfo] = None
    out_info: Optional[TensorsInfo] = None
    preprocess: Optional[Callable[..., Any]] = None
    postprocess: Optional[Callable[..., Any]] = None
    metadata: Dict[str, Any] = field(default_factory=dict)

    def fn(self) -> Callable[..., Any]:
        """Params-closed pure function over input arrays."""
        params = self.params
        apply = self.apply
        if params is None:
            return apply
        return lambda *xs: apply(params, *xs)


def init_variables(module: Any, seed: int, *dummies: Any) -> Any:
    """One-dispatch model init: the whole flax ``init`` traces into a
    single compiled XLA program. Eager init runs hundreds of tiny device
    ops — minutes over a high-RTT TPU tunnel; jitted it is one compile +
    one execute."""
    import jax

    fn = jax.jit(lambda key: module.init(key, *dummies))
    return fn(jax.random.PRNGKey(int(seed)))


def register_model(name: str, factory: Callable[..., ModelBundle]) -> None:
    with _lock:
        _factories[name.lower()] = factory


def model_names() -> List[str]:
    _ensure_builtin_models()
    with _lock:
        return sorted(_factories)


def get_model(spec: str, **overrides: Any) -> ModelBundle:
    """Resolve "zoo://name?opt=val" or bare "name"."""
    _ensure_builtin_models()
    s = spec
    if s.startswith("zoo://"):
        s = s[len("zoo://"):]
    if "?" in s:
        s, qs = s.split("?", 1)
        opts = {k: v[0] for k, v in urllib.parse.parse_qs(qs).items()}
    else:
        opts = {}
    opts.update(overrides)
    with _lock:
        factory = _factories.get(s.lower())
    if factory is None:
        raise ValueError(f"unknown zoo model {spec!r}; known: {model_names()}")
    return factory(**opts)


_builtins_loaded = False


def _ensure_builtin_models() -> None:
    global _builtins_loaded
    if _builtins_loaded:
        return
    _builtins_loaded = True
    from . import mobilenet_v2  # noqa: F401
    from . import simple  # noqa: F401
    from . import ssd_mobilenet  # noqa: F401
    from . import deeplab  # noqa: F401
    from . import posenet  # noqa: F401
    from . import lstm  # noqa: F401
    from . import stream_transformer  # noqa: F401
