"""Autoscale policies — pure, deterministic scale decisions.

A policy is a function of the controller's observed signals (replica
count, aggregate queue depth, worst engine occupancy, SLO breach list)
to a :class:`Decision`. Policies hold their own anti-flap state —
hysteresis (N consecutive pressure ticks before acting), cooldown
(minimum quiet period between actions), and a deadband between the
scale-up and scale-in thresholds where the only legal answer is
``hold`` — so the controller itself stays a dumb reconcile loop.

Everything is clock-injectable and free of I/O: ``decide()`` on the
same tick sequence always yields the same action sequence, which is
what the seeded-chaos acceptance test pins.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Tuple

__all__ = ["Decision", "AutoscalePolicy", "PricedPolicy", "POLICIES",
           "parse_autoscale_spec"]


@dataclass
class Decision:
    """One policy verdict: ``action`` is ``"scale_up"``, ``"scale_in"``
    or ``"hold"``; ``reason`` is the human/journal explanation."""

    action: str
    reason: str
    count: int = 1
    signals: Dict[str, Any] = field(default_factory=dict)


class AutoscalePolicy:
    """Threshold policy with hysteresis, cooldown, and a deadband.

    Pressure definition: a tick is *up-pressure* when queue depth,
    occupancy, or an SLO breach exceeds the high thresholds;
    *down-pressure* when queue depth AND occupancy sit below the low
    thresholds with no breach. The gap between the two threshold pairs
    is the deadband — inside it both streaks reset and the policy
    holds, so a signal oscillating around one threshold can never flap
    the fleet. Acting requires ``hysteresis`` consecutive pressure
    ticks AND ``cooldown_s`` elapsed since the last action.
    """

    name = "default"

    def __init__(self, min_replicas: int, max_replicas: int, *,
                 queue_high: float = 8.0, queue_low: float = 1.0,
                 occupancy_high: float = 0.85, occupancy_low: float = 0.30,
                 hysteresis: int = 2, cooldown_s: float = 10.0,
                 clock: Callable[[], float] = time.monotonic) -> None:
        if min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if max_replicas < min_replicas:
            raise ValueError("max_replicas must be >= min_replicas")
        if queue_low > queue_high or occupancy_low > occupancy_high:
            raise ValueError("low thresholds must not exceed high "
                             "(the gap is the deadband)")
        if hysteresis < 1:
            raise ValueError("hysteresis must be >= 1")
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.queue_high, self.queue_low = float(queue_high), float(queue_low)
        self.occupancy_high = float(occupancy_high)
        self.occupancy_low = float(occupancy_low)
        self.hysteresis = int(hysteresis)
        self.cooldown_s = float(cooldown_s)
        self._clock = clock
        self._up_streak = 0
        self._down_streak = 0
        self._last_action_t: Optional[float] = None

    # -- pressure classification ------------------------------------------

    def _pressure(self, signals: Dict[str, Any]) -> Tuple[str, str]:
        """-> (direction, why) with direction in up/down/deadband."""
        queue = float(signals.get("queue_depth", 0.0) or 0.0)
        occ = float(signals.get("occupancy", 0.0) or 0.0)
        breached = signals.get("breached") or ()
        if breached:
            return "up", f"slo breach: {','.join(sorted(breached))}"
        if queue >= self.queue_high:
            return "up", f"queue {queue:g} >= {self.queue_high:g}"
        if occ >= self.occupancy_high:
            return "up", f"occupancy {occ:.2f} >= {self.occupancy_high:.2f}"
        if queue <= self.queue_low and occ <= self.occupancy_low:
            return "down", (f"queue {queue:g} <= {self.queue_low:g} and "
                            f"occupancy {occ:.2f} <= {self.occupancy_low:.2f}")
        return "deadband", "between thresholds"

    # -- the verdict ------------------------------------------------------

    def decide(self, signals: Dict[str, Any]) -> Decision:
        replicas = int(signals.get("replicas", 0) or 0)
        direction, why = self._pressure(signals)
        if direction == "up":
            self._up_streak += 1
            self._down_streak = 0
        elif direction == "down":
            self._down_streak += 1
            self._up_streak = 0
        else:  # deadband: both streaks reset — no slow drift into action
            self._up_streak = self._down_streak = 0
            return self._hold(why, signals)

        now = self._clock()
        if self._last_action_t is not None \
                and now - self._last_action_t < self.cooldown_s:
            return self._hold(f"cooldown ({why})", signals)
        if direction == "up":
            if replicas >= self.max_replicas:
                return self._hold(f"at max_replicas ({why})", signals)
            if self._up_streak < self.hysteresis:
                return self._hold(
                    f"hysteresis {self._up_streak}/{self.hysteresis} "
                    f"({why})", signals)
            return self._act("scale_up", why, signals, now)
        if replicas <= self.min_replicas:
            return self._hold(f"at min_replicas ({why})", signals)
        if self._down_streak < self.hysteresis:
            return self._hold(
                f"hysteresis {self._down_streak}/{self.hysteresis} "
                f"({why})", signals)
        return self._act("scale_in", why, signals, now)

    def _hold(self, reason: str, signals: Dict[str, Any]) -> Decision:
        return Decision("hold", reason, count=0, signals=dict(signals))

    def _act(self, action: str, reason: str, signals: Dict[str, Any],
             now: float) -> Decision:
        self._last_action_t = now
        self._up_streak = self._down_streak = 0
        return Decision(action, reason, count=1, signals=dict(signals))


class PricedPolicy(AutoscalePolicy):
    """Cost-model-priced variant: a scale-up must *pay for itself*.

    Spawning a replica costs ``spawn_cost_s`` (process start + compile
    warmup); a replica retires backlog at ``service_rate`` items/s. A
    scale-up is only worth it when the modeled time-to-drain of the
    current backlog on the current fleet exceeds the spawn cost — i.e.
    the new replica would come up before the queue clears anyway.
    Scale-in additionally prices the migration bill: holding one
    replica briefly is cheaper than migrating a large session census,
    so big-census down-pressure holds until the census shrinks or
    ``max_migration_sessions`` covers it.
    """

    name = "priced"

    def __init__(self, min_replicas: int, max_replicas: int, *,
                 spawn_cost_s: float = 5.0, service_rate: float = 4.0,
                 max_migration_sessions: int = 64, **kw: Any) -> None:
        super().__init__(min_replicas, max_replicas, **kw)
        if spawn_cost_s <= 0 or service_rate <= 0:
            raise ValueError("spawn_cost_s and service_rate must be > 0")
        self.spawn_cost_s = float(spawn_cost_s)
        self.service_rate = float(service_rate)
        self.max_migration_sessions = int(max_migration_sessions)

    def decide(self, signals: Dict[str, Any]) -> Decision:
        d = super().decide(signals)
        if d.action == "scale_up":
            replicas = max(1, int(signals.get("replicas", 1) or 1))
            queue = float(signals.get("queue_depth", 0.0) or 0.0)
            drain_s = queue / (replicas * self.service_rate)
            if not signals.get("breached") and drain_s < self.spawn_cost_s:
                # backlog clears before the new replica would be ready;
                # cooldown stamp stands, so this can't immediately re-fire
                return self._hold(
                    f"priced out: drain {drain_s:.1f}s < spawn "
                    f"{self.spawn_cost_s:.1f}s", signals)
            d.reason += f" (drain {drain_s:.1f}s >= spawn" \
                        f" {self.spawn_cost_s:.1f}s)" if queue else ""
        elif d.action == "scale_in":
            census = int(signals.get("victim_sessions", 0) or 0)
            if census > self.max_migration_sessions:
                return self._hold(
                    f"priced out: {census} sessions to migrate > "
                    f"{self.max_migration_sessions}", signals)
        return d


#: policy name -> class, the ``MIN:MAX[:policy]`` third field
POLICIES: Dict[str, type] = {
    "default": AutoscalePolicy,
    "priced": PricedPolicy,
}


def parse_autoscale_spec(spec: str) -> Tuple[int, int, str]:
    """Parse ``MIN:MAX[:policy]`` (the ``--autoscale`` argument).

    -> ``(min_replicas, max_replicas, policy_name)``; raises
    ``ValueError`` with a usage-ready message on any malformed spec.
    """
    parts = str(spec).split(":")
    if len(parts) not in (2, 3):
        raise ValueError(f"autoscale spec {spec!r}: want MIN:MAX[:policy]")
    try:
        mn, mx = int(parts[0]), int(parts[1])
    except ValueError:
        raise ValueError(f"autoscale spec {spec!r}: MIN and MAX must be "
                         "integers") from None
    if mn < 1:
        raise ValueError(f"autoscale spec {spec!r}: MIN must be >= 1")
    if mx < mn:
        raise ValueError(f"autoscale spec {spec!r}: MAX must be >= MIN")
    policy = parts[2] if len(parts) == 3 else "default"
    if policy not in POLICIES:
        raise ValueError(f"autoscale spec {spec!r}: unknown policy "
                         f"{policy!r} (one of {sorted(POLICIES)})")
    return mn, mx, policy
