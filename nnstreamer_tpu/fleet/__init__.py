"""fleet/ — SLO-driven autoscaling with live drain and zero-loss
stream migration.

The first subsystem that *acts* on the telemetry arc: obs/slo.py burns,
obs/fleet.py routing_view load, and sched/ engine occupancy feed a
reconcile-loop :class:`~nnstreamer_tpu.fleet.controller.FleetController`
that scales a routed backend set up and in through a pluggable, priced
policy (fleet/autoscale.py) — and migrates live sessions off draining
backends over the existing KV_PAGE_XFER wire (fleet/migrate.py) so a
scale-in never kills a stream.

fleet/checkpoint.py extends the arc to crashes: a
:class:`~nnstreamer_tpu.fleet.checkpoint.CheckpointDaemon` snapshots
live sessions into a pluggable store, and when the aggregator
tombstones an instance without a drain the controller's ``restore``
reconcile action re-pins its sessions onto survivors and splices the
freshest valid checkpoint back in (stale/missing falls back to
re-prefill, token-identically either way). ``upgrade()`` rides the
same machinery for rolling upgrades: checkpoint → drain one →
terminate → relaunch behind ``/readyz`` → confirm via the SLO burn
tap → next.

Zero-overhead contract: the only hot-path wiring is the module global
:data:`AUTOSCALE_HOOK`, gated exactly like ``TUNE_HOOK`` —

    hook = _fleet.AUTOSCALE_HOOK
    if hook is not None:
        hook.observe_occupancy(...)

one attribute load and a None test when autoscaling is off.
``enable()`` / ``disable()`` are the only writers of the hook
(enforced by nnslint's fleet rule).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from .autoscale import (POLICIES, AutoscalePolicy, Decision, PricedPolicy,
                        parse_autoscale_spec)

__all__ = ["AUTOSCALE_HOOK", "AutoscalePolicy", "PricedPolicy", "Decision",
           "POLICIES", "parse_autoscale_spec", "enable", "disable",
           "enabled", "controller", "snapshot"]

#: the None-gated controller hook. None (the default) means no wired
#: site — sched's occupancy sampler, the exporter's debug route, the
#: push-doc journal — pays more than one attribute load. Assigned only
#: by :func:`enable`/:func:`disable` below (nnslint ownership rule).
AUTOSCALE_HOOK: Optional["Any"] = None


def enable(router: Any, min_replicas: int, max_replicas: int, *,
           policy: str = "default", launcher: Any = None,
           aggregator: Any = None, start: bool = False,
           policy_kw: Optional[Dict[str, Any]] = None,
           **kw: Any) -> Any:
    """Build and install the process-global fleet controller.

    ``policy`` names an entry of :data:`POLICIES`; ``policy_kw``
    reaches its constructor (thresholds, hysteresis, cooldown), extra
    ``**kw`` the controller's. An injected ``clock`` is shared with
    the policy unless ``policy_kw`` overrides it — one fake clock
    drives the whole decision path. The obs/fleet.py
    ``FLEET_ACTIONS_HOOK`` is installed so the action journal rides
    push docs; ``start=True`` also spins the background reconcile
    thread (tests drive ``reconcile_once()`` by hand instead).
    """
    global AUTOSCALE_HOOK
    if AUTOSCALE_HOOK is not None:
        return AUTOSCALE_HOOK
    from .controller import FleetController

    pkw = dict(policy_kw or {})
    if "clock" in kw:
        pkw.setdefault("clock", kw["clock"])
    pol = POLICIES[policy](min_replicas, max_replicas, **pkw)
    ctl = FleetController(router, pol, launcher=launcher,
                          aggregator=aggregator, **kw)
    # the journal federates exactly like tune configs: a None-gated
    # module hook on obs/fleet.py, carried in every push doc
    from ..obs import fleet as _obsfleet

    _obsfleet.FLEET_ACTIONS_HOOK = ctl.actions
    AUTOSCALE_HOOK = ctl
    if start:
        ctl.start()
    return ctl


def disable() -> None:
    """Uninstall the controller and stop its reconcile thread."""
    global AUTOSCALE_HOOK
    ctl = AUTOSCALE_HOOK
    AUTOSCALE_HOOK = None
    from ..obs import fleet as _obsfleet

    _obsfleet.FLEET_ACTIONS_HOOK = None
    if ctl is not None:
        ctl.stop()


def enabled() -> bool:
    return AUTOSCALE_HOOK is not None


def controller() -> Optional[Any]:
    return AUTOSCALE_HOOK


def snapshot() -> Optional[Dict[str, Any]]:
    """The ``/debug/fleet/actions`` payload (None when off)."""
    ctl = AUTOSCALE_HOOK
    return None if ctl is None else ctl.snapshot()
