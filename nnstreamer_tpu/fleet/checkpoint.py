"""Crash checkpoints + restore — survive kill -9 with warm sessions.

fleet/migrate.py made *graceful* scale-in lossless: drain → export →
ship → re-pin. A backend killed -9 skips every one of those steps — no
freeze, no export round trip — and until now every session it owned
paid a full re-prefill on its new home. This module closes that gap
with the classic two halves:

**Checkpoint** (:class:`CheckpointDaemon`): periodically — and only
when a session committed new tokens since its last snapshot — export
each live session's recorded token path plus the KV pages covering it
(``LMEngine.checkpoint_session``, a read-only walk that never freezes
admission) into a pluggable :class:`CheckpointStore`. Blobs are
self-describing and self-verifying: one JSON header line (session,
monotone per-session sequence number = committed path length, token
path, page geometry) followed by the raw page payload, with a blake2b
digest over both — a truncated or bit-flipped blob is rejected at
parse, never spliced. :class:`LocalDirStore` writes them
atomically (tmp + ``os.replace``) with bounded per-session retention;
:class:`NeighborStore` — the production default — ships each blob to
neighbor workers over the existing ``Cmd.KV_PAGE_XFER`` wire
(``meta["checkpoint"]`` frames; serving/disagg.py files them into the
receiving worker's attached store), so a worker's state survives the
loss of its own host.

**Restore** (:class:`SessionRestorer`): when the aggregator tombstones
an instance that never drained, the controller's ``restore`` reconcile
action re-pins the dead worker's owned sessions onto survivors
(``BackendSet.repin_dead_owner``) and, per session, asks each survivor
to forward its newest stored checkpoint to the session's new home
(``lm_ctl: checkpoint_send`` → a ``meta["restore"]`` page frame the
target splices and adopts). Staleness is decided against the
tombstone's last pushed checkpoint watermark: a blob older than what
the dead worker last claimed to have stored is refused, and the
session falls back to today's re-prefill absorb — token-identically
either way (greedy decode is a pure function of the token history the
client resends), the checkpoint only buys back the cache warmth. The
diag critical path bills the first post-restore prefill as ``restore``
or ``re_prefill`` accordingly, and
``nnstpu_fleet_restored_sessions_total{outcome=...}`` counts which
path each session took.

Zero-overhead contract: nothing here touches the decode hot path. The
daemon reads ``session_watermarks()`` (a dict comprehension over the
bounded session table) under the worker's engine lock at its own
cadence; the only global is ``obs.fleet.CHECKPOINT_HOOK`` (push-doc
watermarks), None-gated like every hook there and assigned only by
this module (nnslint ``naming/checkpoint`` rule).
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..core.log import logger
from ..graph.element import join_or_warn
from ..obs import events as _events
from ..obs import fleet as _obsfleet
from ..obs import metrics as _obs
from ..obs import tracing as _tracing
from ..query.protocol import QueryProtocolError
from ..resilience import policy as _rp
from .migrate import LM_CAPS

log = logger("fleet")

#: blob format version — bumped on any header/payload layout change;
#: parse refuses newer versions instead of misreading them
BLOB_VERSION = 1
#: newest checkpoints kept per session (older ones are the corruption
#: fallback chain, not an archive)
DEFAULT_RETENTION = 4
#: daemon cadence when run as a thread
DEFAULT_INTERVAL_S = 5.0

_reg = _obs.registry()
_CKPT_SESSIONS = _reg.counter(
    "nnstpu_fleet_checkpoint_sessions_total",
    "Session checkpoints written (one per session per daemon pass that"
    " saw new committed tokens)")
_CKPT_BYTES = _reg.counter(
    "nnstpu_fleet_checkpoint_bytes_total",
    "Checkpoint blob bytes written to stores (header + page payload)")
_CKPT_SECONDS = _reg.histogram(
    "nnstpu_fleet_checkpoint_seconds",
    "One daemon pass: snapshot + blob build + store put, all sessions")
_CKPT_REJECTS = _reg.counter(
    "nnstpu_fleet_checkpoint_reject_total",
    "Stored blobs refused at parse (never spliced)", ("reason",))
_RESTORED = _reg.counter(
    "nnstpu_fleet_restored_sessions_total",
    "Sessions re-homed off a dead (non-drained) worker, by which path"
    " rebuilt their state", ("outcome",))
_RESTORE_SECONDS = _reg.histogram(
    "nnstpu_fleet_restore_seconds",
    "Per-session crash restore wall time (survivor scan + page splice"
    " or fallback adoption)")


# --------------------------------------------------------------------------- #
# Blob format: one JSON header line + raw page payload, digest over both
# --------------------------------------------------------------------------- #

def _digest(header: Dict[str, Any], payload: bytes) -> str:
    h = hashlib.blake2b(digest_size=16)
    h.update(json.dumps(header, sort_keys=True,
                        separators=(",", ":")).encode())
    h.update(payload)
    return h.hexdigest()


def build_blob(session: str, seq: int, path: Any,
               doc: Optional[Dict[str, Any]]) -> bytes:
    """Serialize one session checkpoint. ``doc`` is the
    ``kv_cache.export_pages`` document (None records the token path
    alone — restore then adopts the path but the prefill recomputes).
    The digest covers the header *and* the payload, so truncation and
    bit flips in either half fail the same check."""
    from ..serving.disagg import encode_pages
    path_list = [int(t) for t in np.asarray(path).reshape(-1)]
    pages_meta, payload = (None, b"")
    if doc is not None and doc.get("entries"):
        pages_meta, payload = encode_pages(doc)
    header: Dict[str, Any] = {
        "v": BLOB_VERSION, "session": str(session), "seq": int(seq),
        "path": path_list, "pages": pages_meta,
    }
    header["digest"] = _digest(header, payload)
    return json.dumps(header, sort_keys=True,
                      separators=(",", ":")).encode() + b"\n" + payload


def parse_blob(blob: bytes) -> Dict[str, Any]:
    """Parse + verify one checkpoint blob.

    Returns ``{"session", "seq", "path", "doc"}`` (``doc`` None when
    the blob carried no pages). Raises ValueError on truncation, a
    digest mismatch, an unknown version, or malformed structure — the
    caller's cue to fall back to the next-older blob."""
    from ..serving.disagg import decode_pages
    head, sep, payload = blob.partition(b"\n")
    if not sep:
        raise ValueError("checkpoint blob truncated before header end")
    try:
        header = json.loads(head)
    except (ValueError, UnicodeDecodeError) as e:
        raise ValueError(f"checkpoint header unreadable: {e}")
    if not isinstance(header, dict):
        raise ValueError("checkpoint header is not an object")
    if int(header.get("v", 0)) > BLOB_VERSION:
        raise ValueError(
            f"checkpoint blob v{header.get('v')} is newer than this "
            f"reader (v{BLOB_VERSION})")
    want = header.pop("digest", None)
    if not want or _digest(header, payload) != want:
        raise ValueError("checkpoint digest mismatch (truncated or "
                         "corrupt blob)")
    session = header.get("session")
    path = header.get("path")
    if not isinstance(session, str) or not isinstance(path, list):
        raise ValueError("checkpoint header missing session/path")
    doc = None
    if header.get("pages") is not None:
        # geometry re-validation: decode_pages refuses a payload whose
        # byte count disagrees with the declared page layout
        doc = decode_pages(header["pages"], payload)
    return {"session": session, "seq": int(header.get("seq", 0)),
            "path": [int(t) for t in path], "doc": doc}


def _reject(reason: str, detail: str) -> None:
    _CKPT_REJECTS.labels(reason).inc()
    _events.record("fleet.checkpoint_reject",
                   f"checkpoint blob refused: {detail}",
                   severity="warning", reason=reason)


# --------------------------------------------------------------------------- #
# Stores
# --------------------------------------------------------------------------- #

class CheckpointStore:
    """Store contract, three methods:

    ``put(session, seq, blob)`` durably files one blob (raises on
    failure — the daemon journals and retries next pass);
    ``latest(session)`` returns the newest blob that *parses and
    verifies* (older blobs are the fallback chain for a corrupt head),
    or None where blobs are not locally readable (NeighborStore);
    ``watermarks()`` maps session → highest stored seq, the slice that
    rides push docs so a restore can judge staleness after the worker
    is gone."""

    def put(self, session: str, seq: int, blob: bytes) -> None:
        raise NotImplementedError

    def latest(self, session: str) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def watermarks(self) -> Dict[str, int]:
        raise NotImplementedError

    def close(self) -> None:  # pragma: no cover - trivial default
        pass


class MemoryStore(CheckpointStore):
    """In-process store: what a worker holds for its neighbors, and
    the test double. Same retention/verification semantics as the dir
    store, minus the filesystem."""

    def __init__(self, retention: int = DEFAULT_RETENTION):
        self.retention = max(1, int(retention))
        self._lock = threading.Lock()
        self._blobs: Dict[str, "OrderedDict[int, bytes]"] = {}

    def put(self, session: str, seq: int, blob: bytes) -> None:
        s = str(session)
        with self._lock:
            per = self._blobs.setdefault(s, OrderedDict())
            per[int(seq)] = bytes(blob)
            per.move_to_end(int(seq))
            while len(per) > self.retention:
                per.popitem(last=False)

    def latest(self, session: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            per = dict(self._blobs.get(str(session)) or {})
        for seq in sorted(per, reverse=True):
            try:
                return parse_blob(per[seq])
            except ValueError as e:
                _reject("verify", f"{session} seq {seq}: {e}")
        return None

    def watermarks(self) -> Dict[str, int]:
        with self._lock:
            return {s: max(per) for s, per in self._blobs.items() if per}


def _session_dirname(session: str) -> str:
    """Filesystem-safe, collision-free directory name for a session id
    (a readable prefix plus a short hash of the exact id)."""
    safe = re.sub(r"[^A-Za-z0-9._-]", "_", str(session))[:48]
    tag = hashlib.blake2b(str(session).encode(), digest_size=4).hexdigest()
    return f"{safe}-{tag}"


class LocalDirStore(CheckpointStore):
    """Directory-backed store: ``root/<session>/<seq>.ckpt``.

    Writes are atomic — blob lands in a dot-tmp sibling, is fsynced,
    then ``os.replace``d into place — so a crash mid-write leaves at
    worst an ignored tmp file, never a half-blob under the real name
    (and a half-blob smuggled in anyway still fails its digest)."""

    def __init__(self, root: str, retention: int = DEFAULT_RETENTION):
        self.root = str(root)
        self.retention = max(1, int(retention))
        self._lock = threading.Lock()
        #: session -> dirname; rebuilt from disk so watermarks survive
        #: the writer process (the whole point of the store)
        self._dirs: Dict[str, str] = {}
        os.makedirs(self.root, exist_ok=True)
        self._rescan()

    def _rescan(self) -> None:
        for d in sorted(os.listdir(self.root)):
            newest = self._newest_blob(os.path.join(self.root, d))
            if newest is None:
                continue
            try:
                with open(newest, "rb") as fp:
                    head = fp.readline()
                session = json.loads(head).get("session")
            except (OSError, ValueError, AttributeError):
                continue
            if isinstance(session, str):
                self._dirs[session] = d

    def _sdir(self, session: str) -> str:
        with self._lock:
            d = self._dirs.setdefault(str(session),
                                      _session_dirname(session))
        return os.path.join(self.root, d)

    @staticmethod
    def _seq_files(sdir: str) -> List[Tuple[int, str]]:
        try:
            names = os.listdir(sdir)
        except OSError:
            return []
        out = []
        for n in names:
            if n.endswith(".ckpt") and not n.startswith("."):
                try:
                    out.append((int(n[:-5]), os.path.join(sdir, n)))
                except ValueError:
                    continue
        return sorted(out)

    def _newest_blob(self, sdir: str) -> Optional[str]:
        files = self._seq_files(sdir)
        return files[-1][1] if files else None

    def put(self, session: str, seq: int, blob: bytes) -> None:
        sdir = self._sdir(session)
        os.makedirs(sdir, exist_ok=True)
        final = os.path.join(sdir, f"{int(seq):012d}.ckpt")
        tmp = os.path.join(sdir, f".{int(seq):012d}.tmp")
        with open(tmp, "wb") as fp:
            fp.write(blob)
            fp.flush()
            os.fsync(fp.fileno())
        os.replace(tmp, final)
        # retention: drop the oldest beyond the bound (never the one
        # just written — seq is monotone per session)
        files = self._seq_files(sdir)
        for _sq, p in files[:max(0, len(files) - self.retention)]:
            try:
                os.unlink(p)
            except OSError:
                pass

    def latest(self, session: str) -> Optional[Dict[str, Any]]:
        for seq, p in reversed(self._seq_files(self._sdir(session))):
            try:
                with open(p, "rb") as fp:
                    return parse_blob(fp.read())
            except (OSError, ValueError) as e:
                _reject("verify", f"{session} seq {seq}: {e}")
        return None

    def watermarks(self) -> Dict[str, int]:
        with self._lock:
            dirs = dict(self._dirs)
        out: Dict[str, int] = {}
        for session, d in dirs.items():
            files = self._seq_files(os.path.join(self.root, d))
            if files:
                out[session] = files[-1][0]
        return out


class NeighborStore(CheckpointStore):
    """The production default: blobs live on *other* workers.

    ``put`` ships the blob to up to ``fanout`` neighbor endpoints as a
    ``meta["checkpoint"]`` frame on the existing KV_PAGE_XFER op; the
    receiving worker files it into its attached store
    (serving/disagg.py). ``latest`` is None by construction — reading
    back happens on the restore path via ``lm_ctl: checkpoint_send``
    against the survivors, not here. Watermarks track what was acked,
    which is exactly what the push doc must claim exists."""

    def __init__(self, endpoints: List[str], *, fanout: int = 1,
                 timeout_s: float = 5.0):
        self.endpoints = [str(e) for e in endpoints]
        self.fanout = max(1, int(fanout))
        self.timeout_s = float(timeout_s)
        self._clients: Dict[str, Any] = {}
        self._lock = threading.Lock()
        self._marks: Dict[str, int] = {}

    def _client(self, endpoint: str) -> Any:
        from ..serving.disagg import PageTransferClient
        from ..query.router import parse_endpoints
        with self._lock:
            c = self._clients.get(endpoint)
            if c is None:
                (host, port), = parse_endpoints(endpoint)
                c = PageTransferClient(host, port, timeout_s=self.timeout_s)
                self._clients[endpoint] = c
        return c

    def put(self, session: str, seq: int, blob: bytes) -> None:
        meta = {"checkpoint": {"v": BLOB_VERSION, "session": str(session),
                               "seq": int(seq)}}
        acked = 0
        for ep in self.endpoints:
            try:
                self._client(ep).send_frame(meta, blob)
                acked += 1
            except (ConnectionError, OSError, QueryProtocolError) as e:
                log.debug("checkpoint ship to %s failed: %s", ep, e)
                with self._lock:
                    c = self._clients.pop(ep, None)
                if c is not None:
                    c.close()
            if acked >= self.fanout:
                break
        if acked == 0:
            raise OSError(
                f"no neighbor accepted checkpoint for {session!r} "
                f"(tried {len(self.endpoints)})")
        with self._lock:
            self._marks[str(session)] = max(
                int(seq), self._marks.get(str(session), 0))

    def latest(self, session: str) -> Optional[Dict[str, Any]]:
        return None  # blobs live on the neighbors; restore asks them

    def watermarks(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._marks)

    def close(self) -> None:
        with self._lock:
            clients, self._clients = list(self._clients.values()), {}
        for c in clients:
            c.close()


# --------------------------------------------------------------------------- #
# CheckpointDaemon
# --------------------------------------------------------------------------- #

class _NullLock:
    def __enter__(self) -> None:
        return None

    def __exit__(self, *exc: Any) -> bool:
        return False


class CheckpointDaemon:
    """Periodic engine snapshotter for one engine.

    ``run_once()`` is the deterministic unit (tests and the bench lane
    call it directly; ``start()`` wraps it in a timer thread): read the
    engine's per-session committed-path watermarks, and for every
    session at least ``min_new_tokens`` past its last checkpoint take a
    read-only snapshot and file it. ``lock`` is the engine's serializer
    (a DisaggWorker passes its ``_elock``) — held only around the two
    engine reads, never across a store put, so a slow store can't stall
    serving. Sequence numbers are the committed token-path length:
    monotone per session with no extra state, and comparable against
    the live engine after the daemon is gone."""

    def __init__(self, engine: Any, store: CheckpointStore, *,
                 interval_s: float = DEFAULT_INTERVAL_S,
                 min_new_tokens: int = 1, lock: Any = None,
                 clock: Callable[[], float] = time.monotonic,
                 name: str = "ckpt") -> None:
        self.engine = engine
        self.store = store
        self.interval_s = float(interval_s)
        self.min_new_tokens = max(1, int(min_new_tokens))
        self.name = name
        self._elock = lock if lock is not None else _NullLock()
        self._clock = clock
        self._last: Dict[str, int] = {}
        self._hook_installed = False
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.stats: Dict[str, int] = {
            "passes": 0, "written": 0, "skipped": 0, "failed": 0}

    def watermarks(self) -> Dict[str, int]:
        """Session → last checkpointed seq — the push-doc slice the
        restore path judges staleness against."""
        return dict(self._last)

    def run_once(self) -> int:
        """One pass; returns checkpoints written."""
        self.stats["passes"] += 1
        t0 = self._clock()
        with self._elock:
            marks = self.engine.session_watermarks()
        written = 0
        for session in sorted(marks):
            seq = int(marks[session])
            if seq < self._last.get(session, 0) + self.min_new_tokens:
                self.stats["skipped"] += 1
                continue
            with self._elock:
                snap = self.engine.checkpoint_session(session)
            if snap is None:
                self.stats["skipped"] += 1
                continue
            path, doc = snap
            # re-derive seq from the snapshot itself: the path may have
            # advanced between the watermark read and the snapshot
            seq = int(np.asarray(path).size)
            blob = build_blob(session, seq, path, doc)
            try:
                self.store.put(session, seq, blob)
            except Exception as e:  # noqa: BLE001 — store is pluggable
                self.stats["failed"] += 1
                _events.record(
                    "fleet.checkpoint_fail",
                    f"checkpoint put failed for {session}: {e}",
                    severity="warning", session=session, error=str(e))
                continue
            self._last[session] = seq
            self.stats["written"] += 1
            written += 1
            _CKPT_SESSIONS.inc()
            _CKPT_BYTES.inc(len(blob))
        if written:
            _CKPT_SECONDS.observe(self._clock() - t0)
            _events.record(
                "fleet.checkpoint_write",
                f"{self.name}: {written} session checkpoint(s) written",
                severity="debug", daemon=self.name, written=written)
        return written

    def install_hook(self) -> None:
        """Publish this daemon's watermarks in push docs (first daemon
        wins — one worker per process is the deployment shape; tests
        pass watermarks explicitly to build_push instead)."""
        if _obsfleet.CHECKPOINT_HOOK is None:
            _obsfleet.CHECKPOINT_HOOK = self.watermarks
            self._hook_installed = True

    def uninstall_hook(self) -> None:
        if self._hook_installed \
                and _obsfleet.CHECKPOINT_HOOK == self.watermarks:
            _obsfleet.CHECKPOINT_HOOK = None
        self._hook_installed = False

    def start(self) -> None:
        if self._thread is not None:
            return
        self.install_hook()
        self._stop.clear()

        def loop() -> None:
            while not self._stop.wait(self.interval_s):
                try:
                    self.run_once()
                except Exception:  # a sick daemon must not crash serving
                    log.exception("checkpoint pass failed")

        self._thread = threading.Thread(
            target=loop, name=f"fleet-ckpt:{self.name}", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        self._thread = None
        if t is not None:
            join_or_warn(t, f"fleet-ckpt:{self.name}", timeout=5.0)
        self.uninstall_hook()


# --------------------------------------------------------------------------- #
# SessionRestorer
# --------------------------------------------------------------------------- #

class SessionRestorer:
    """Re-homes a dead (non-drained) worker's sessions onto survivors
    and splices their newest valid checkpoints in.

    Driven by the controller's ``restore`` reconcile action with the
    tombstone's endpoint + checkpoint watermarks. Per session: re-pin
    (``repin_dead_owner``), then ask each survivor — new home first,
    it may hold the blob itself — to forward its stored checkpoint to
    the new home (``lm_ctl: checkpoint_send`` with ``min_seq`` = the
    watermark, so anything older than the dead worker's last claimed
    checkpoint is refused as stale). No survivor fresh enough →
    fallback: the new home adopts the session for re-prefill
    (``lm_ctl: adopt_session``), exactly the migrate absorb path."""

    def __init__(self, router: Any, *, caps: str = LM_CAPS,
                 timeout_s: float = 10.0,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.router = router
        self.caps = caps
        self.timeout_s = float(timeout_s)
        self._clock = clock
        self.stats: Dict[str, int] = {"restored": 0, "re_prefilled": 0}

    def restore_instance(self, instance: str, endpoint: str,
                         watermarks: Optional[Dict[str, int]] = None,
                         deadline: Optional[_rp.Deadline] = None
                         ) -> Dict[str, Any]:
        """Restore every session the dead ``endpoint`` owned. Returns
        the action report the controller journals."""
        t0 = self._clock()
        marks = {str(s): int(q) for s, q in (watermarks or {}).items()}
        _events.record(
            "fleet.restore_start",
            f"instance {instance} ({endpoint}) died without drain; "
            f"restoring its sessions onto survivors",
            severity="warning", instance=instance, endpoint=endpoint)
        # census + re-pin BEFORE severing: remove() drops the ownership
        # tables this reads
        moved = self.router.backends.repin_dead_owner(endpoint)
        try:
            self.router.remove_backend(endpoint, drain=False)
        except KeyError:
            pass
        survivors = {be.endpoint: be
                     for be in self.router.backends.backends()
                     if be.state == "active"}
        sessions: List[Dict[str, Any]] = []
        for session, target_ep in moved:
            ts = self._clock()
            dl = deadline or _rp.Deadline.after_s(self.timeout_s)
            outcome, seq = self._restore_one(
                session, target_ep, marks.get(session, 0), survivors, dl)
            dt = self._clock() - ts
            _RESTORED.labels(outcome).inc()
            _RESTORE_SECONDS.observe(dt)
            self.stats["restored" if outcome == "checkpoint"
                       else "re_prefilled"] += 1
            sessions.append({"session": session, "target": target_ep,
                             "outcome": outcome, "seq": seq,
                             "seconds": dt})
        report = {
            "instance": instance, "endpoint": endpoint,
            "sessions": sessions,
            "restored": sum(1 for s in sessions
                            if s["outcome"] == "checkpoint"),
            "re_prefilled": sum(1 for s in sessions
                                if s["outcome"] == "re_prefill"),
            "seconds": self._clock() - t0,
        }
        _events.record(
            "fleet.restore_done",
            f"instance {instance}: {report['restored']} session(s) "
            f"restored from checkpoint, {report['re_prefilled']} fell "
            f"back to re-prefill",
            instance=instance, endpoint=endpoint,
            restored=report["restored"],
            re_prefilled=report["re_prefilled"])
        return report

    def _restore_one(self, session: str, target_ep: str, min_seq: int,
                     survivors: Dict[str, Any], dl: _rp.Deadline
                     ) -> Tuple[str, int]:
        span = _tracing.start_span(
            "fleet.restore", parent=_tracing.current_context(),
            attrs={"session": session, "target": target_ep})
        outcome, seq = "re_prefill", 0
        try:
            order = [ep for ep in sorted(survivors) if ep == target_ep]
            order += [ep for ep in sorted(survivors) if ep != target_ep]
            for ep in order:
                meta = {"lm_ctl": {"op": "checkpoint_send",
                                   "session": session,
                                   "xfer_to": target_ep,
                                   "min_seq": int(min_seq)},
                        _rp.WIRE_KEY: dl.to_wire()}
                try:
                    rmeta, _ = survivors[ep].request(meta, b"", self.caps)
                except (ConnectionError, OSError, QueryProtocolError):
                    continue
                if rmeta.get("sent"):
                    outcome, seq = "checkpoint", int(rmeta.get("seq", 0))
                    break
            if outcome != "checkpoint":
                # stale / missing / ship failed everywhere: the new
                # home adopts the session cold and re-prefills
                tgt = survivors.get(target_ep)
                if tgt is not None:
                    try:
                        tgt.request(
                            {"lm_ctl": {"op": "adopt_session",
                                        "session": session,
                                        "restored": False}},
                            b"", self.caps)
                    except (ConnectionError, OSError,
                            QueryProtocolError):
                        pass
                _events.record(
                    "fleet.restore_fallback",
                    f"session {session}: no checkpoint >= seq "
                    f"{min_seq} on any survivor; re-prefill absorb",
                    severity="warning", session=session,
                    target=target_ep, min_seq=int(min_seq))
        finally:
            span.set_attribute("outcome", outcome)
            span.end()
        return outcome, seq
