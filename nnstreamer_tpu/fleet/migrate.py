"""Live session migration — move a session's KV state between backends
with zero stream loss.

The protocol, per session, is one control round trip plus one page
shipment over wires that already exist:

1. ``lm_ctl: {op: "export_session"}`` to the SOURCE backend: the worker
   freezes the session (new submits are refused, so the router's
   failover lands them on the target under the ORIGINAL deadline),
   exports the session's KV pages for its recorded token path
   (``LMEngine.export_session``), and ships them to the target over the
   existing ``Cmd.KV_PAGE_XFER`` op — the same op and splice path
   disagg's prefill→decode hand-off uses.
2. Re-pin the router's session affinity to the target
   (``BackendSet.pin_session``), so the next buffer dials the target
   directly instead of paying a lazy failover round trip.

Absorb path: if the source dies mid-migration (connection error, or
the page transfer itself fails), the pin still moves — the target
simply re-prefills the session's next prompt from scratch, exactly
disagg's reprefill semantics. The stream never dies; it only loses the
cache warmth the migration would have preserved. Greedy decoding is a
pure function of the token sequence, so outputs stay token-for-token
identical either way (the acceptance test pins this).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional

from ..core.log import logger
from ..obs import events as _events
from ..obs import metrics as _obs
from ..obs import tracing as _tracing
from ..query.protocol import QueryProtocolError
from ..resilience import policy as _rp

log = logger("fleet")

#: capability string for the lm_ctl control op — the disagg LM wire
LM_CAPS = "disagg/lm"

_reg = _obs.registry()
_MIGRATED_TOTAL = _reg.counter(
    "nnstpu_fleet_migrated_sessions_total",
    "Sessions re-pinned off a draining backend", ("outcome",))
_MIGRATION_SECONDS = _reg.histogram(
    "nnstpu_fleet_migration_seconds",
    "Per-session migration wall time (export + ship + re-pin)")


class SessionMigrator:
    """Migrates sessions between a router's backends.

    Stateless apart from stats; every decision is driven by the caller
    (the controller picks victims and targets), so migrations are
    exactly as deterministic as the caller's schedule. ``clock`` is
    injectable for tests.
    """

    def __init__(self, router: Any, *,
                 timeout_s: float = 10.0,
                 caps: str = LM_CAPS,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.router = router
        self.timeout_s = float(timeout_s)
        self.caps = caps
        self._clock = clock
        self.stats: Dict[str, int] = {
            "migrated": 0, "absorbed": 0, "pages_moved": 0}

    def migrate(self, session: str, source: Any, target: Any,
                deadline: Optional[_rp.Deadline] = None) -> Dict[str, Any]:
        """Move ``session`` from ``source`` to ``target`` (Backend
        objects). Always re-pins; returns a result doc with ``ok``
        (export+ship landed) and ``absorbed`` (target must re-prefill).
        """
        dl = deadline or _rp.Deadline.after_s(self.timeout_s)
        _events.record("fleet.migrate_start",
                       f"session {session}: {source.endpoint} -> "
                       f"{target.endpoint}",
                       session=session, source=source.endpoint,
                       target=target.endpoint)
        span = _tracing.start_span(
            "fleet.migrate", parent=_tracing.current_context(),
            attrs={"session": session, "source": source.endpoint,
                   "target": target.endpoint})
        t0 = self._clock()
        pages, err = 0, None
        try:
            meta: Dict[str, Any] = {
                "lm_ctl": {"op": "export_session", "session": session,
                           "xfer_to": target.endpoint},
                _rp.WIRE_KEY: dl.to_wire(),
            }
            rmeta, _ = source.request(meta, b"", self.caps)
            pages = int(rmeta.get("pages_sent", 0) or 0)
            if rmeta.get("xfer_error"):
                err = str(rmeta["xfer_error"])
        except (ConnectionError, OSError, QueryProtocolError) as e:
            err = f"{type(e).__name__}: {e}"
        # the pin moves regardless — a dead source must not strand the
        # session on a backend that can no longer serve it
        self.router.backends.pin_session(session, target.endpoint)
        dt = self._clock() - t0
        absorbed = err is not None
        span.set_attribute("pages", pages)
        span.set_attribute("absorbed", absorbed)
        span.end()
        _MIGRATION_SECONDS.observe(dt)
        if absorbed:
            self.stats["absorbed"] += 1
            _MIGRATED_TOTAL.labels("absorbed").inc()
            _events.record("fleet.migrate_abandon",
                           f"session {session}: source export failed, "
                           f"target will re-prefill ({err})",
                           severity="warning", session=session,
                           source=source.endpoint, target=target.endpoint,
                           error=err)
            log.warning("migrate %s: absorb path (%s)", session, err)
        else:
            self.stats["migrated"] += 1
            self.stats["pages_moved"] += pages
            _MIGRATED_TOTAL.labels("migrated").inc()
            _events.record("fleet.migrate_done",
                           f"session {session}: {pages} pages to "
                           f"{target.endpoint} in {dt * 1e3:.1f}ms",
                           session=session, target=target.endpoint,
                           pages=pages, seconds=dt)
        return {"session": session, "ok": not absorbed,
                "absorbed": absorbed, "pages": pages,
                "seconds": dt, "error": err,
                "source": source.endpoint, "target": target.endpoint}
