"""The reconcile loop: observe → decide → act, with a bounded action
journal that rides fleet push docs.

:class:`FleetController` closes the telemetry arc — burn rates
(obs/slo.py), queue depths + routable census (obs/fleet.py's
aggregator), and engine occupancy (sched/engine.py's
``AUTOSCALE_HOOK`` callback) flow IN; backend add/drain/remove and live
session migration (fleet/migrate.py) flow OUT through the router.
Every action is journaled (``/debug/fleet/actions``), priced by the
policy (fleet/autoscale.py), gated by a circuit breaker
(``_rp.fleet_breaker_name``), and bounded by a deadline — an
autoscaler that hangs or flaps is worse than none.

Determinism contract: ``reconcile_once()`` with an injectable clock is
a pure function of the observed signals and policy state — the
acceptance test drives ticks by hand and the background thread
(``start()``) is just ``reconcile_once`` on a timer.
"""

from __future__ import annotations

import http.client
import socket
import subprocess
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

from ..core.log import logger
from ..graph.element import join_or_warn
from ..obs import diag as _diag
from ..obs import events as _events
from ..obs import metrics as _obs
from ..resilience import policy as _rp
from .autoscale import AutoscalePolicy, Decision
from .migrate import SessionMigrator

log = logger("fleet")

_reg = _obs.registry()
_REPLICAS = _reg.gauge(
    "nnstpu_fleet_worker_replicas",
    "Active backend replicas under controller management", ("controller",))
_SCALE_ACTIONS = _reg.counter(
    "nnstpu_fleet_scale_actions_total",
    "Reconcile actions taken (and skips, labeled)",
    ("controller", "action"))


def _free_port(host: str) -> int:
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    try:
        s.bind((host, 0))
        return s.getsockname()[1]
    finally:
        s.close()


@dataclass
class LaunchHandle:
    """One launched worker: its query endpoint, readiness port, and the
    process to terminate on scale-in."""

    endpoint: str
    ready_port: int
    proc: Any = None


class BackendLauncher:
    """Subprocess launcher with readiness gating on ``/readyz``.

    ``argv_template`` is the worker command with ``{host}``, ``{port}``
    (query wire) and ``{ready_port}`` (metrics exporter) placeholders —
    e.g. ``["python", "-m", "worker", "--port", "{port}", "--metrics",
    "{ready_port}"]``. ``launch()`` picks free ports, spawns, then
    polls ``http://host:ready_port/readyz`` until it answers 200 (the
    exporter's readiness contract) before handing the endpoint to the
    router — a backend is never routable before it can serve.
    """

    def __init__(self, argv_template: List[str], *,
                 host: str = "127.0.0.1", ready_timeout_s: float = 30.0,
                 poll_interval_s: float = 0.1) -> None:
        self.argv_template = list(argv_template)
        self.host = host
        self.ready_timeout_s = float(ready_timeout_s)
        self.poll_interval_s = float(poll_interval_s)

    def launch(self) -> LaunchHandle:
        port, ready_port = _free_port(self.host), _free_port(self.host)
        argv = [a.format(host=self.host, port=port, ready_port=ready_port)
                for a in self.argv_template]
        proc = subprocess.Popen(argv)
        handle = LaunchHandle(f"{self.host}:{port}", ready_port, proc)
        try:
            self._await_ready(handle)
        except Exception:
            self.terminate(handle)
            raise
        return handle

    def _await_ready(self, handle: LaunchHandle) -> None:
        t_end = time.monotonic() + self.ready_timeout_s
        while time.monotonic() < t_end:
            if handle.proc is not None and handle.proc.poll() is not None:
                raise RuntimeError(
                    f"worker {handle.endpoint} exited rc="
                    f"{handle.proc.returncode} before ready")
            try:
                conn = http.client.HTTPConnection(
                    self.host, handle.ready_port, timeout=1.0)
                try:
                    conn.request("GET", "/readyz")
                    if conn.getresponse().status == 200:
                        return
                finally:
                    conn.close()
            except OSError:
                pass
            time.sleep(self.poll_interval_s)
        raise TimeoutError(
            f"worker {handle.endpoint} not ready within "
            f"{self.ready_timeout_s:.0f}s")

    def terminate(self, handle: LaunchHandle) -> None:
        proc = handle.proc
        if proc is None or proc.poll() is not None:
            return
        proc.terminate()
        try:
            proc.wait(timeout=5.0)
        except Exception:
            proc.kill()


class FleetController:
    """SLO-driven reconcile loop over a :class:`QueryRouter`.

    ``launcher`` is anything with ``launch() -> handle`` (the handle
    exposing ``.endpoint``) and ``terminate(handle)`` —
    :class:`BackendLauncher` for real subprocess workers, or an
    in-process shim in tests. Without one the controller still drains,
    migrates, and scales in; scale-up decisions journal as skipped.
    """

    def __init__(self, router: Any, policy: AutoscalePolicy, *,
                 launcher: Any = None, aggregator: Any = None,
                 migrator: Optional[SessionMigrator] = None,
                 restorer: Any = None,
                 interval_s: float = 1.0,
                 drain_timeout_s: float = 30.0,
                 journal_limit: int = 256,
                 name: str = "fleet",
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.router = router
        self.policy = policy
        self.launcher = launcher
        self.aggregator = aggregator
        self.migrator = migrator or SessionMigrator(router, clock=clock)
        # built lazily (fleet/checkpoint.py import) on the first dead
        # instance — controllers that never see a crash never pay it
        self._restorer = restorer
        self.interval_s = float(interval_s)
        self.drain_timeout_s = float(drain_timeout_s)
        self.name = name
        self._clock = clock
        self._breaker = _rp.CircuitBreaker(_rp.fleet_breaker_name(name))
        self._journal: deque = deque(maxlen=int(journal_limit))
        self._seq = 0
        #: the signal snapshot the CURRENT tick decided on — journaled
        #: with every action so each entry records the evidence
        #: (occupancy, burn, census) that crossed the threshold
        self._last_signals: Dict[str, Any] = {}
        self._occ: Dict[str, float] = {}
        self._lock = threading.Lock()
        self._launched: Dict[str, LaunchHandle] = {}
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.stats: Dict[str, int] = {
            "ticks": 0, "scale_up": 0, "scale_in": 0, "holds": 0,
            "migrations": 0, "restores": 0, "upgrades": 0}

    # -- signals (IN) -----------------------------------------------------

    def observe_occupancy(self, engine: str, occupancy: float) -> None:
        """The sched ``AUTOSCALE_HOOK`` target: latest busy fraction per
        engine, sampled at batch boundaries."""
        with self._lock:
            self._occ[str(engine)] = float(occupancy)

    def observe(self) -> Dict[str, Any]:
        """One consistent signal snapshot for the policy."""
        active = [be for be in self.router.backends.backends()
                  if be.state == "active"]
        signals: Dict[str, Any] = {
            "replicas": len(active),
            "queue_depth": 0.0,
            "occupancy": 0.0,
            "breached": [],
            "routable": len(active),
        }
        with self._lock:
            if self._occ:
                signals["occupancy"] = max(self._occ.values())
        if self.aggregator is not None:
            agg = self.aggregator.scale_signals()
            signals["queue_depth"] = agg.get("queue_depth", 0.0)
            signals["breached"] = agg.get("breached", [])
            signals["routable"] = agg.get("routable", len(active))
        if active:
            victim = self._pick_victim(active)
            signals["victim_sessions"] = len(
                self.router.backends.sessions_owned(victim.endpoint))
        return signals

    # -- the loop ---------------------------------------------------------

    def reconcile_once(self) -> Decision:
        """One deterministic tick: restore the dead, then
        observe → decide → act → journal."""
        self.stats["ticks"] += 1
        # crash-restore BEFORE observing: a just-tombstoned instance
        # must be re-pinned onto survivors before the policy reads the
        # census, or one tick of decisions is made against ghosts
        self.restore_dead()
        signals = self.observe()
        self._last_signals = signals
        decision = self.policy.decide(signals)
        _REPLICAS.labels(self.name).set(float(signals["replicas"]))
        if decision.action == "scale_up":
            self._scale_up(decision)
        elif decision.action == "scale_in":
            self._scale_in(decision)
        else:
            self.stats["holds"] += 1
        return decision

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def loop() -> None:
            while not self._stop.wait(self.interval_s):
                try:
                    self.reconcile_once()
                except Exception:  # a sick controller must not crash serving
                    log.exception("reconcile tick failed")

        self._thread = threading.Thread(
            target=loop, name="fleet-controller", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        self._thread = None
        if t is not None:
            join_or_warn(t, f"fleet:{self.name}", timeout=5.0)

    # -- actions (OUT) ----------------------------------------------------

    def _journal_add(self, action: str, reason: str,
                     **extra: Any) -> Dict[str, Any]:
        self._seq += 1
        entry = {"seq": self._seq, "t": self._clock(), "action": action,
                 "reason": reason,
                 "signals": dict(self._last_signals), **extra}
        self._journal.append(entry)
        _SCALE_ACTIONS.labels(self.name, action).inc()
        dhook = _diag.DIAG_HOOK
        if dhook is not None:
            # real scale/migrate actions are diag capture triggers
            # (the hook ignores skips/holds); the journaled entry rides
            # inside the bundle's cause detail
            dhook.on_fleet_action(action, entry)
        return entry

    def actions(self) -> List[Dict[str, Any]]:
        """The bounded action journal — the ``FLEET_ACTIONS_HOOK``
        target (rides push docs) and the ``/debug/fleet/actions``
        payload."""
        return list(self._journal)

    def _scale_up(self, decision: Decision) -> None:
        if not self._breaker.allow():
            self._journal_add("scale_up_skipped",
                              f"breaker open ({decision.reason})")
            return
        if self.launcher is None:
            self._journal_add("scale_up_skipped",
                              f"no launcher ({decision.reason})")
            return
        try:
            handle = self.launcher.launch()
            self.router.add_backend(handle.endpoint)
        except Exception as e:
            self._breaker.record_failure()
            self._journal_add("scale_up_failed",
                              f"{type(e).__name__}: {e}")
            _events.record("fleet.scale_up",
                           f"launch failed: {e}", severity="warning",
                           controller=self.name, error=str(e))
            return
        self._breaker.record_success()
        self._launched[handle.endpoint] = handle
        self._register_kill(handle)
        self.stats["scale_up"] += 1
        self._journal_add("scale_up", decision.reason,
                          endpoint=handle.endpoint)
        _events.record("fleet.scale_up",
                       f"added {handle.endpoint}: {decision.reason}",
                       controller=self.name, endpoint=handle.endpoint)

    def _pick_victim(self, active: List[Any]) -> Any:
        """Deterministic scale-in victim: fewest owned sessions, then
        lexicographic endpoint — same snapshot, same victim."""
        owned = self.router.backends.sessions_owned
        return min(active, key=lambda be: (len(owned(be.endpoint)),
                                           be.endpoint))

    def _scale_in(self, decision: Decision) -> None:
        active = [be for be in self.router.backends.backends()
                  if be.state == "active"]
        if len(active) < 2:
            self._journal_add("scale_in_skipped", "single replica")
            return
        victim = self._pick_victim(active)
        sessions = self.router.backends.sessions_owned(victim.endpoint)
        migrated: List[Dict[str, Any]] = []
        dl = _rp.Deadline.after_s(self.drain_timeout_s)
        for s in sorted(sessions):
            target = self.router.backends.pick(
                session=s, exclude={victim.endpoint})
            if target is None:
                continue
            migrated.append(self.migrator.migrate(s, victim, target,
                                                  deadline=dl))
            self.stats["migrations"] += 1
        # drain AFTER migration: the sessions are already re-pinned, so
        # the eager drain re-pin finds nothing left to move
        try:
            self.router.remove_backend(victim.endpoint, drain=True)
        except KeyError:
            pass
        if self.aggregator is not None:
            self.aggregator.confirm_drain(victim.instance
                                          or victim.endpoint)
        handle = self._launched.pop(victim.endpoint, None)
        if handle is not None and self.launcher is not None:
            self.launcher.terminate(handle)
        self._unregister_kill(victim.endpoint)
        self.stats["scale_in"] += 1
        self._journal_add(
            "scale_in", decision.reason, endpoint=victim.endpoint,
            migrated=sum(1 for m in migrated if m["ok"]),
            absorbed=sum(1 for m in migrated if m["absorbed"]))
        _events.record("fleet.scale_in",
                       f"drained {victim.endpoint}: {decision.reason} "
                       f"({len(migrated)} sessions migrated)",
                       controller=self.name, endpoint=victim.endpoint,
                       sessions=len(migrated))

    # -- crash restore ----------------------------------------------------

    def _register_kill(self, handle: Any) -> None:
        """Expose a launched subprocess to the chaos ``kill`` fault so
        the crash-restore acceptance test can SIGKILL it by endpoint.
        Registration is a dict insert — free when chaos is off."""
        proc = getattr(handle, "proc", None)
        if proc is None:
            return
        from ..resilience import chaos as _chaos
        _chaos.register_kill_target(handle.endpoint, proc)

    def _unregister_kill(self, endpoint: str) -> None:
        from ..resilience import chaos as _chaos
        _chaos.unregister_kill_target(endpoint)

    def _restorer_get(self) -> Any:
        if self._restorer is None:
            from .checkpoint import SessionRestorer
            self._restorer = SessionRestorer(self.router,
                                             clock=self._clock)
        return self._restorer

    def restore_dead(self) -> List[Dict[str, Any]]:
        """The ``restore`` reconcile action: claim every tombstoned
        instance the aggregator declared dead-without-drain, re-pin its
        sessions onto survivors, and splice checkpoints (fresh) or fall
        back to re-prefill (stale/missing) — see fleet/checkpoint.py.

        ``consume_restore`` is an atomic first-caller-wins claim, so
        concurrent controllers (or a tick racing the background thread)
        never restore the same instance twice.
        """
        if self.aggregator is None:
            return []
        reports: List[Dict[str, Any]] = []
        for row in self.aggregator.restorables():
            payload = self.aggregator.consume_restore(row["instance"])
            if payload is None:
                continue  # another claimant won the race
            ep = payload["endpoint"]
            # reap the corpse first: a dead subprocess handle must not
            # linger as a terminate target or a chaos kill victim
            handle = self._launched.pop(ep, None)
            if handle is not None and self.launcher is not None:
                self.launcher.terminate(handle)
            self._unregister_kill(ep)
            try:
                report = self._restorer_get().restore_instance(
                    payload["instance"], ep,
                    payload.get("checkpoints"),
                    deadline=_rp.Deadline.after_s(self.drain_timeout_s))
            except Exception as e:
                self._journal_add("restore_failed",
                                  f"{type(e).__name__}: {e}", endpoint=ep)
                log.exception("restore of %s failed", ep)
                continue
            self.aggregator.confirm_drain(payload["instance"])
            self.stats["restores"] += 1
            self._journal_add(
                "restore",
                f"instance {payload['instance']} died at {ep}",
                endpoint=ep, sessions=report["sessions"],
                restored=report["restored"],
                re_prefilled=report["re_prefilled"])
            reports.append(report)
        return reports

    # -- rolling upgrade --------------------------------------------------

    def upgrade(self, *,
                checkpoint: Optional[Callable[[], Any]] = None
                ) -> Dict[str, Any]:
        """Rolling upgrade: for each active backend in turn —
        checkpoint → drain one → terminate → relaunch behind the
        launcher's ``/readyz`` gate → confirm → next.

        ``checkpoint`` is an optional pre-drain tick (usually the
        :class:`~..fleet.checkpoint.CheckpointDaemon`'s ``run_once``)
        so every session has a fresh snapshot before its owner goes
        down — a mid-upgrade crash then restores instead of
        re-prefilling. Confirmation is the SLO burn tap: any breached
        window after a step aborts the remaining plan, leaving the
        fleet in a mixed-version but healthy state.
        """
        plan = sorted(be.endpoint
                      for be in self.router.backends.backends()
                      if be.state == "active")
        report: Dict[str, Any] = {"plan": list(plan), "upgraded": [],
                                  "aborted": None}
        if self.launcher is None:
            report["aborted"] = "no launcher"
            self._journal_add("upgrade_skipped", "no launcher")
            return report
        self._journal_add("upgrade_start", f"{len(plan)} backend(s)",
                          plan=list(plan))
        _events.record("fleet.upgrade",
                       f"rolling upgrade of {len(plan)} backend(s)",
                       controller=self.name, backends=len(plan))
        for ep in plan:
            victim = next((be for be in self.router.backends.backends()
                           if be.endpoint == ep and be.state == "active"),
                          None)
            if victim is None:
                continue  # vanished since the plan snapshot
            if checkpoint is not None:
                try:
                    checkpoint()
                except Exception:
                    log.exception("pre-drain checkpoint tick failed")
            # drain one: live-migrate every owned session, then drain
            dl = _rp.Deadline.after_s(self.drain_timeout_s)
            migrated = 0
            for s in sorted(self.router.backends.sessions_owned(ep)):
                target = self.router.backends.pick(session=s,
                                                   exclude={ep})
                if target is None:
                    continue
                m = self.migrator.migrate(s, victim, target, deadline=dl)
                migrated += 1 if m["ok"] else 0
                self.stats["migrations"] += 1
            try:
                self.router.remove_backend(ep, drain=True)
            except KeyError:
                pass
            if self.aggregator is not None:
                self.aggregator.confirm_drain(victim.instance or ep)
            # terminate the old worker
            handle = self._launched.pop(ep, None)
            if handle is not None:
                self.launcher.terminate(handle)
            self._unregister_kill(ep)
            # relaunch: launch() blocks behind the /readyz gate, so the
            # replacement is never routable before it can serve
            try:
                new = self.launcher.launch()
                self.router.add_backend(new.endpoint)
            except Exception as e:
                report["aborted"] = f"relaunch failed: {e}"
                self._journal_add("upgrade_abort",
                                  f"relaunch after {ep} failed: {e}",
                                  endpoint=ep)
                _events.record("fleet.upgrade",
                               f"aborted: relaunch after {ep} failed: {e}",
                               severity="warning", controller=self.name,
                               endpoint=ep)
                return report
            self._launched[new.endpoint] = new
            self._register_kill(new)
            report["upgraded"].append({"old": ep, "new": new.endpoint,
                                       "migrated": migrated})
            self._journal_add("upgrade_step", f"{ep} -> {new.endpoint}",
                              old=ep, new=new.endpoint, migrated=migrated)
            # confirm: the SLO burn tap decides whether to continue
            if self.aggregator is not None:
                breached = self.aggregator.scale_signals().get(
                    "breached", [])
                if breached:
                    report["aborted"] = f"slo breach: {breached}"
                    self._journal_add(
                        "upgrade_abort",
                        f"SLO burn breached after {ep}: {breached}",
                        endpoint=ep, breached=list(breached))
                    _events.record(
                        "fleet.upgrade",
                        f"aborted after {ep}: SLO burn {breached}",
                        severity="warning", controller=self.name,
                        endpoint=ep)
                    return report
        self.stats["upgrades"] += 1
        self._journal_add("upgrade_done",
                          f"{len(report['upgraded'])} backend(s) upgraded")
        _events.record("fleet.upgrade",
                       f"done: {len(report['upgraded'])} backend(s)",
                       controller=self.name,
                       backends=len(report["upgraded"]))
        return report

    def snapshot(self) -> Dict[str, Any]:
        """The ``/debug/fleet/actions`` payload."""
        with self._lock:
            occ = dict(self._occ)
        return {
            "controller": self.name,
            "policy": type(self.policy).name,
            "min_replicas": self.policy.min_replicas,
            "max_replicas": self.policy.max_replicas,
            "stats": dict(self.stats),
            "occupancy": occ,
            "migrator": dict(self.migrator.stats),
            "actions": self.actions(),
        }
