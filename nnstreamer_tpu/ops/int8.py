"""Dynamic-activation int8 matmul (w8a8) for the MXU's double-rate path.

``models/quantize.py`` (``quant=w8``) is weight-ONLY: int8 weights are
dequantized inside the program and the matmul itself runs bf16 — a
bandwidth win, compute unchanged. This module is the compute-side
complement: both operands are int8 and the contraction runs on the
MXU's int8 path, which on TPU v5e is **2x the bf16 peak** (394 TOPS vs
197 TFLOP/s; measured on this chip: ~326 TOPS vs ~176 TFLOP/s on an
8192³ matmul chain — see docs/performance.md).

Recipe (the standard dynamic-quant serving scheme):

* weights: per-output-channel absmax int8, quantized ONCE at load
  (`quantize_weight`) — same grid as quantize.py's w8;
* activations: per-row (per-token) absmax int8, quantized dynamically
  inside the program right before each GEMM (`quant_act`) — the
  quantize/rescale elementwise work fuses around the dot;
* accumulation: exact int32 (``preferred_element_type``), rescaled to
  float by the outer product of the two scale vectors.

Because int32 accumulation is EXACT (no float contraction-order drift),
two execution forms that disagree only in how they batch the same GEMMs
(prefill vs step decode, single-stream vs vmapped slots) produce
bit-identical quantized GEMM results — the causal-LM family's
exactness-between-forms contract survives quantization (pinned by
tests/test_lm_w8a8.py).

The reference serves quantized models through TFLite's int8 kernels
(tensor_filter_tensorflow_lite.cc; mobilenet_*_quant.tflite test
models); this is the TPU-idiomatic equivalent for the transformer
serving path.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

#: dict key tagging a w8a8-quantized weight leaf (int8 payload under the
#: tag, f32 per-output-channel scales under "s") — a zero-collision
#: marker the shared matmul sites dispatch on
W8A8_TAG = "__w8a8__"


def quantize_weight(w: Any) -> Dict[str, jax.Array]:
    """(…, K, N) float weight → ``{W8A8_TAG: int8, "s": f32 (…, N)}``.

    Per-output-channel absmax over the contracted axis K, the same grid
    as quantize.py's weight-only path. Leading axes (e.g. a layer stack
    L) pass through, so a scanned stack slices into per-layer dicts.
    """
    w = jnp.asarray(w)
    if w.ndim < 2:
        raise ValueError(f"quantize_weight: need rank>=2, got {w.shape}")
    absmax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=-2)
    scale = jnp.where(absmax == 0.0, 1.0, absmax / 127.0)
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale[..., None, :]),
                 -127, 127).astype(jnp.int8)
    return {W8A8_TAG: q, "s": scale.astype(jnp.float32)}


def is_quantized(w: Any) -> bool:
    return isinstance(w, dict) and W8A8_TAG in w


def stack_shape(w: Any) -> Tuple[int, ...]:
    """Shape of a weight leaf, quantized or not (the int8 payload keeps
    the float weight's shape, so introspection sites stay one-liners)."""
    return w[W8A8_TAG].shape if is_quantized(w) else w.shape


def quant_act(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Per-row dynamic activation quant: (…, K) float → (int8, f32
    (…, 1) scales). Rows are tokens at every call site, so each token
    gets its own grid — the scheme's accuracy comes from this."""
    xf = x.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
    s = jnp.where(absmax == 0.0, 1.0, absmax / 127.0)
    q = jnp.clip(jnp.round(xf / s), -127, 127).astype(jnp.int8)
    return q, s


def int8_matmul(x: jax.Array, w: Dict[str, jax.Array]) -> jax.Array:
    """x (…, K) float @ quantized w (K, N) → (…, N) in x's dtype.

    int8·int8→int32 on the MXU's double-rate path; the surrounding
    quant/rescale is elementwise and fuses."""
    xq, xs = quant_act(x)
    y = jax.lax.dot_general(
        xq, w[W8A8_TAG], (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    return (y.astype(jnp.float32) * xs * w["s"]).astype(x.dtype)


def quant_act_global(x: jax.Array, axis_name: str
                     ) -> Tuple[jax.Array, jax.Array]:
    """`quant_act` for an activation whose logical row is COLUMN-SHARDED
    across a mesh axis (each device holds a slice of the row): the
    per-row absmax is taken locally then ``lax.pmax``-ed over the axis,
    so every device quantizes its slice on the same GLOBAL grid — the
    grid a single device would have used on the full row. This is what
    makes a tensor-parallel int8 GEMM bit-identical to its single-device
    form (parallel/tp_decode.py): same grid → same int8 codes → the
    int32 partials psum exactly."""
    xf = x.astype(jnp.float32)
    absmax = jax.lax.pmax(
        jnp.max(jnp.abs(xf), axis=-1, keepdims=True), axis_name)
    s = jnp.where(absmax == 0.0, 1.0, absmax / 127.0)
    q = jnp.clip(jnp.round(xf / s), -127, 127).astype(jnp.int8)
    return q, s


def int8_partial(xq: jax.Array, wq: jax.Array) -> jax.Array:
    """Exact int32 partial products of a ROW-SHARDED int8 GEMM: this
    device's slice of the contraction. The caller ``psum``s the int32
    partials (integer addition — exact, no reduction-order drift) and
    rescales with the global grids afterwards."""
    return jax.lax.dot_general(
        xq, wq, (((xq.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)


def int8_row_sharded_matmul(x: jax.Array, wq: jax.Array,
                            w_scale: jax.Array, axis_name: str
                            ) -> jax.Array:
    """The distributed w8a8 GEMM for a ROW-SHARDED weight: x (…, K_local)
    float on this device @ int8 rows wq (K_local, N), with the
    REPLICATED global per-output-channel grid w_scale (N,). Activation
    codes come from the pmax-global grid, partials are summed in exact
    int32 across the axis, then rescaled once — bit-identical to the
    single-device `int8_matmul` over the full contraction. The ONE
    definition of the TP int8 scheme (tp_decode's token step and
    tp_prefill share it)."""
    xq, xs = quant_act_global(x, axis_name)
    tot = jax.lax.psum(int8_partial(xq, wq), axis_name)
    return (tot.astype(jnp.float32) * xs * w_scale).astype(x.dtype)


def mlp_matmul(x: jax.Array, w1: Any, w2: Any) -> jax.Array:
    """The transformer MLP block ``gelu(x @ w1) @ w2`` as one fused unit.

    Float weights take the ordinary composition. When BOTH weights are
    w8a8 dicts, the two GEMMs run int8 on the MXU and the inter-GEMM
    elementwise chain — dequant by ``xs·w1.s``, gelu, dynamic per-row
    requant — collapses into one Pallas epilogue kernel
    (``ops.pallas.epilogue.dequant_gelu_requant``), so the hidden
    activation never round-trips HBM in float between the matmuls.
    Bit-identical to ``matmul_any(gelu(matmul_any(x, w1)), w2)``: the
    kernel (and its CPU reference) composes exactly that math — int32
    accumulation is exact, and gelu/requant run in the same dtypes and
    order as the unfused form (pinned by tests/test_epilogue.py)."""
    if not (is_quantized(w1) and is_quantized(w2)):
        return matmul_any(jax.nn.gelu(matmul_any(x, w1)), w2)
    from .pallas import epilogue as _ep

    xq, xs = quant_act(x)
    y = jax.lax.dot_general(
        xq, w1[W8A8_TAG], (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    hq, hs = _ep.dequant_gelu_requant(y, xs, w1["s"], out_dtype=x.dtype)
    y2 = jax.lax.dot_general(
        hq, w2[W8A8_TAG], (((hq.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    return (y2.astype(jnp.float32) * hs * w2["s"]).astype(x.dtype)


def matmul_any(x: jax.Array, w: Any) -> jax.Array:
    """``x @ w`` that dispatches on the leaf: float weights take the
    ordinary (bf16/f32) MXU path, w8a8 dicts take the int8 path. The
    ONE matmul used by every causal-LM execution form, so passing a
    `quantize_lm_params` tree through ANY of them — forward, prefill
    (dense/flash/ring), decode step, verify window, vmapped slots —
    serves int8 with zero flag-threading."""
    if is_quantized(w):
        return int8_matmul(x, w)
    return x @ w
