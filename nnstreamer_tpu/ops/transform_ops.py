"""tensor_transform operator library, lowered to XLA.

Reference: gst/nnstreamer/elements/gsttensortransform.c (modes
dimchg/typecast/arithmetic/transpose/stand/clamp, tensor_transform.h:57-84).
The reference hand-vectorizes with Orc codegen (transform-orc.orc) when
``acceleration=true``; here every mode builds a pure jax function and XLA
fuses the whole chain into one kernel — on TPU these ride the VPU and fuse
into neighboring MXU ops, which is the point of lowering the pipeline's
elementwise stages instead of running them on host.

Option-string grammar matches the reference:
  * typecast:   "float32"
  * arithmetic: "typecast:float32,add:-127.5,div:127.5" (chained ops; values
                may be per-channel lists "add:1;2;3")
  * transpose:  "1:0:2:3" — permutation in reference dim order (innermost
                first); output dim i takes input dim perm[i]
  * dimchg:     "0:2" — move dim position a to position b (reference dim idx)
  * stand:      "default" | "dc-average" [":per-channel"]
  * clamp:      "min:max"

All dims in options use the reference's innermost-first convention and are
translated to row-major numpy axes internally.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence, Tuple

import numpy as np

from ..core.types import TensorDType, TensorInfo


def _np_axis(rank: int, nns_dim_index: int) -> int:
    """Reference dim index (0 = innermost) → numpy axis."""
    return rank - 1 - nns_dim_index


def _parse_value(s: str):
    """Scalar or ';'-separated per-channel vector."""
    if ";" in s:
        return np.array([float(v) for v in s.split(";")], np.float32)
    return float(s)


class Transform:
    """One parsed transform stage: jax-traceable ``fn`` + static out-info."""

    def __init__(self, fn: Callable[[Any], Any],
                 out_info_fn: Callable[[TensorInfo], TensorInfo],
                 descr: str):
        self.fn = fn
        self.out_info = out_info_fn
        self.descr = descr

    def __repr__(self) -> str:
        return f"Transform({self.descr})"


def build(mode: str, option: str) -> Transform:
    mode = mode.strip().lower()
    if mode == "typecast":
        return _typecast(option)
    if mode == "arithmetic":
        return _arithmetic(option)
    if mode == "transpose":
        return _transpose(option)
    if mode == "dimchg":
        return _dimchg(option)
    if mode == "stand":
        return _stand(option)
    if mode == "clamp":
        return _clamp(option)
    raise ValueError(f"unknown transform mode {mode!r}")


# --------------------------------------------------------------------------- #

def _typecast(option: str) -> Transform:
    dtype = TensorDType.parse(option)
    import jax.numpy as jnp

    target = jnp.dtype(dtype.np_dtype)

    def fn(x):
        return x.astype(target)

    return Transform(fn, lambda i: TensorInfo(i.dims, dtype, i.name),
                     f"typecast:{dtype}")


_ARITH_OPS = {"add", "sub", "mul", "div"}


def _arithmetic(option: str) -> Transform:
    """Chained "typecast:T,add:V,mul:V,div:V" ops, evaluated in order
    (reference gst_tensor_transform arithmetic chain)."""
    import jax.numpy as jnp

    steps: List[Tuple[str, Any]] = []
    out_dtype: Optional[TensorDType] = None
    for part in option.split(","):
        part = part.strip()
        if not part:
            continue
        if ":" not in part:
            raise ValueError(f"arithmetic op needs value: {part!r}")
        op, val = part.split(":", 1)
        op = op.strip().lower()
        if op == "typecast":
            dt = TensorDType.parse(val)
            steps.append(("typecast", jnp.dtype(dt.np_dtype)))
            out_dtype = dt
        elif op in _ARITH_OPS:
            steps.append((op, _parse_value(val)))
        else:
            raise ValueError(f"unknown arithmetic op {op!r}")
    if not steps:
        raise ValueError("empty arithmetic option")

    def fn(x):
        for op, val in steps:
            if op == "typecast":
                x = x.astype(val)
            elif op == "add":
                x = x + val
            elif op == "sub":
                x = x - val
            elif op == "mul":
                x = x * val
            elif op == "div":
                x = x / val
        return x

    def out_info(i: TensorInfo) -> TensorInfo:
        return TensorInfo(i.dims, out_dtype or i.dtype, i.name)

    return Transform(fn, out_info, f"arithmetic:{option}")


def _transpose(option: str) -> Transform:
    perm_nns = [int(x) for x in option.split(":")]
    rank = len(perm_nns)
    if sorted(perm_nns) != list(range(rank)):
        raise ValueError(f"transpose option must be a permutation: {option!r}")
    import jax.numpy as jnp

    # output nns-dim i = input nns-dim perm[i]  →  row-major axes:
    # out axis (rank-1-i) takes input axis (rank-1-perm[i])
    np_perm = [0] * rank
    for i, p in enumerate(perm_nns):
        np_perm[rank - 1 - i] = rank - 1 - p

    def fn(x):
        if x.ndim != rank:
            raise ValueError(
                f"transpose rank mismatch: option rank {rank}, tensor rank {x.ndim}")
        return jnp.transpose(x, np_perm)

    def out_info(i: TensorInfo) -> TensorInfo:
        if i.rank != rank:
            raise ValueError(
                f"transpose rank mismatch: option rank {rank} vs {i.rank}")
        dims = tuple(i.dims[p] for p in perm_nns)
        return TensorInfo(dims, i.dtype, i.name)

    return Transform(fn, out_info, f"transpose:{option}")


def _dimchg(option: str) -> Transform:
    a_str, b_str = option.split(":")
    a, b = int(a_str), int(b_str)
    import jax.numpy as jnp

    def fn(x):
        rank = x.ndim
        return jnp.moveaxis(x, _np_axis(rank, a), _np_axis(rank, b))

    def out_info(i: TensorInfo) -> TensorInfo:
        dims = list(i.dims)
        dims.insert(b, dims.pop(a))
        return TensorInfo(tuple(dims), i.dtype, i.name)

    return Transform(fn, out_info, f"dimchg:{option}")


def _stand(option: str) -> Transform:
    import jax.numpy as jnp

    parts = [p.strip().lower() for p in option.split(":")] if option else ["default"]
    scheme = parts[0] or "default"
    per_channel = len(parts) > 1 and parts[1] == "per-channel"
    if scheme not in ("default", "dc-average"):
        raise ValueError(f"unknown stand scheme {scheme!r}")

    def fn(x):
        xf = x.astype(jnp.float32)
        # channel axis = innermost (reference dim[0]) = last row-major axis
        axes = tuple(range(xf.ndim - 1)) if per_channel else None
        mean = jnp.mean(xf, axis=axes, keepdims=per_channel)
        if scheme == "dc-average":
            return xf - mean
        std = jnp.std(xf, axis=axes, keepdims=per_channel)
        return (xf - mean) / (std + 1e-10)

    return Transform(fn,
                     lambda i: TensorInfo(i.dims, TensorDType.FLOAT32, i.name),
                     f"stand:{option}")


def _clamp(option: str) -> Transform:
    lo_s, hi_s = option.split(":")
    lo, hi = float(lo_s), float(hi_s)
    if lo > hi:
        raise ValueError(f"clamp min > max: {option!r}")
    import jax.numpy as jnp

    def fn(x):
        # bounds cast to the INPUT dtype: the reference's clamp is typed
        # scalar math that preserves the tensor type (python-float bounds
        # would weakly promote int streams to float32). For int streams
        # the bounds are first clamped into the dtype's representable
        # range — a raw cast would WRAP (uint8 with lo=-50 → 206 > hi)
        # and flatten the whole tensor to a constant.
        l, h = lo, hi
        if jnp.issubdtype(x.dtype, jnp.integer):
            info = jnp.iinfo(x.dtype)
            l = int(np.clip(l, info.min, info.max))
            h = int(np.clip(h, info.min, info.max))
        return jnp.clip(x, jnp.asarray(l, x.dtype),
                        jnp.asarray(h, x.dtype))

    return Transform(fn, lambda i: i, f"clamp:{option}")


def compose(transforms: Sequence[Transform]) -> Transform:
    """Fuse a chain of transforms into one (XLA compiles it as one kernel)."""
    if len(transforms) == 1:
        return transforms[0]

    def fn(x):
        for t in transforms:
            x = t.fn(x)
        return x

    def out_info(i: TensorInfo) -> TensorInfo:
        for t in transforms:
            i = t.out_info(i)
        return i

    return Transform(fn, out_info, "+".join(t.descr for t in transforms))
