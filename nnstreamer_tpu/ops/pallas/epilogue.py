"""Pallas TPU kernels for the post-filter epilogue hot path (ops.epilogue).

The filter→transform/decoder tail is where streaming pipelines lose their
roofline after the GEMMs ("Pushing Tensor Accelerators Beyond MatMul",
PAPERS.md): SSD box decode + greedy NMS, classification argmax/top-k,
segmentation colorize, and w8a8 dequant→activation→requant chains either
ran as unfused lax ops or on host NumPy. These kernels back the epilogue
fuser (ops/epilogue.py) and the decoders' device-reduce paths:

  * ``nms_sweep``            — greedy NMS alive-sweep over the top-K
    score-sorted candidates (IoU matrix + sequential suppression).
  * ``class_reduce``         — per-anchor best class score + index
    (argmax/max over the class axis).
  * ``segment_colorize``     — per-pixel argmax over class logits + RGBA
    palette lookup via a one-hot MXU matmul.
  * ``dequant_gelu_requant`` — int32 GEMM accumulator → f32 dequant →
    gelu → per-row int8 requant, keeping the w8a8 MLP int8 end-to-end.

Every kernel has a jnp reference used off-TPU and for interpret-mode
correctness tests; fused callers rely on the references matching the
unfused lax/NumPy paths bit-for-bit, so change them in lockstep with
their consumers (decoders/bounding_box.py, decoders/image_segment.py,
ops/int8.py).
"""

from __future__ import annotations

import functools
from typing import Any, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...obs import profile as _profile


def _on_tpu() -> bool:
    try:
        dev = jax.devices()[0]
    except Exception:  # noqa: BLE001
        return False
    return "tpu" in dev.platform.lower() or "TPU" in str(dev.device_kind)


_LANE = 128


# --------------------------------------------------------------------------- #
# nms_sweep: greedy suppression sweep over score-descending candidates
# --------------------------------------------------------------------------- #

def nms_sweep_reference(x0: jax.Array, y0: jax.Array, x1: jax.Array,
                        y1: jax.Array, scores: jax.Array,
                        iou_threshold: float, threshold: float) -> jax.Array:
    """Scores after greedy NMS: suppressed/below-threshold rows become -1.

    Candidates must already be score-descending (lax.top_k order); the
    sweep then matches decoders.util.nms exactly: a row is kept iff no
    earlier *kept* row overlaps it with IoU strictly above the threshold.
    """
    k = scores.shape[0]
    area = (x1 - x0) * (y1 - y0)
    ix = (jnp.minimum(x1[:, None], x1[None, :])
          - jnp.maximum(x0[:, None], x0[None, :]))
    iy = (jnp.minimum(y1[:, None], y1[None, :])
          - jnp.maximum(y0[:, None], y0[None, :]))
    inter = jnp.clip(ix, 0) * jnp.clip(iy, 0)
    union = area[:, None] + area[None, :] - inter
    iou = jnp.where(union > 0, inter / union, 0.0)
    later = jnp.arange(k)[None, :] > jnp.arange(k)[:, None]
    suppresses = (iou > iou_threshold) & later

    def body(i, alive):
        return alive & ~(alive[i] & suppresses[i])

    alive = jax.lax.fori_loop(0, k, body, scores >= threshold)
    return jnp.where(alive, scores, -1.0)


def _nms_kernel(rows_ref, o_ref, *, k: int, iou_thr: float, threshold: float):
    rows = rows_ref[...]                       # (kp, 128) f32, cols 0-4 used
    x0, y0 = rows[:, 0:1], rows[:, 1:2]
    x1, y1 = rows[:, 2:3], rows[:, 3:4]
    sc = rows[:, 4:5]
    area = (x1 - x0) * (y1 - y0)               # (kp, 1)
    ix = jnp.minimum(x1, x1.T) - jnp.maximum(x0, x0.T)   # (kp, kp)
    iy = jnp.minimum(y1, y1.T) - jnp.maximum(y0, y0.T)
    inter = jnp.clip(ix, 0) * jnp.clip(iy, 0)
    union = area + area.T - inter
    iou = jnp.where(union > 0, inter / union, 0.0)
    kp = rows.shape[0]
    later = (jax.lax.broadcasted_iota(jnp.int32, (kp, kp), 1)
             > jax.lax.broadcasted_iota(jnp.int32, (kp, kp), 0))
    suppresses = (iou > iou_thr) & later

    def body(i, alive):
        sup_i = jax.lax.dynamic_slice_in_dim(suppresses, i, 1, 0)   # (1, kp)
        alive_i = jax.lax.dynamic_slice_in_dim(alive, i, 1, 0)      # (1, 1)
        return alive & ~(alive_i & sup_i.T)

    alive = jax.lax.fori_loop(0, k, body, sc >= threshold)
    out = jnp.where(alive, sc, -1.0)
    o_ref[...] = jnp.broadcast_to(out, (kp, _LANE))


def nms_sweep(x0: jax.Array, y0: jax.Array, x1: jax.Array, y1: jax.Array,
              scores: jax.Array, *, iou_threshold: float, threshold: float,
              interpret: bool = False) -> jax.Array:
    """Greedy-NMS sweep on the VPU; jnp fallback off-TPU.

    K is the PRE_NMS_TOPK candidate budget (≤ a few hundred), so the
    whole (K, K) IoU matrix fits one VMEM block — no grid.
    """
    if not (interpret or _on_tpu()):
        return nms_sweep_reference(x0, y0, x1, y1, scores,
                                   iou_threshold, threshold)
    if _profile.KERNEL_HOOK is not None:  # trace-time kernel label
        _profile.KERNEL_HOOK("pallas.nms_sweep", scores.shape, scores.dtype)
    from jax.experimental import pallas as pl

    k = scores.shape[0]
    kp = max(8, -(-k // 8) * 8)
    rows = jnp.zeros((kp, _LANE), jnp.float32)
    for col, v in enumerate((x0, y0, x1, y1)):
        rows = rows.at[:k, col].set(v.astype(jnp.float32))
    rows = rows.at[:k, 4].set(scores.astype(jnp.float32))
    if kp != k:
        rows = rows.at[k:, 4].set(-1.0)  # pad rows dead: never kept/suppress
    out = pl.pallas_call(
        functools.partial(_nms_kernel, k=k, iou_thr=float(iou_threshold),
                          threshold=float(threshold)),
        out_shape=jax.ShapeDtypeStruct((kp, _LANE), jnp.float32),
        interpret=interpret,
    )(rows)
    return out[:k, 0]


# --------------------------------------------------------------------------- #
# class_reduce: best class score + index per anchor
# --------------------------------------------------------------------------- #

def class_reduce_reference(cls: jax.Array) -> Tuple[jax.Array, jax.Array]:
    return jnp.max(cls, axis=-1), jnp.argmax(cls, axis=-1)


def _class_reduce_kernel(x_ref, s_ref, i_ref, *, l: int):
    x = x_ref[...]                                    # (bn, lp) f32
    best = jnp.max(x, axis=1, keepdims=True)          # (bn, 1)
    iota = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    # first-max index == argmax tie-break
    idx = jnp.min(jnp.where(x == best, iota, l), axis=1, keepdims=True)
    s_ref[...] = jnp.broadcast_to(best, s_ref.shape)
    i_ref[...] = jnp.broadcast_to(idx, i_ref.shape)


def class_reduce(cls: jax.Array,
                 interpret: bool = False) -> Tuple[jax.Array, jax.Array]:
    """(N, L) class scores → (best_score (N,), best_index (N,))."""
    if not (interpret or _on_tpu()):
        return class_reduce_reference(cls)
    if _profile.KERNEL_HOOK is not None:  # trace-time kernel label
        _profile.KERNEL_HOOK("pallas.class_reduce", cls.shape, cls.dtype)
    from jax.experimental import pallas as pl

    n, l = cls.shape
    lp = -(-l // _LANE) * _LANE
    block_rows = min(max(8, -(-n // 8) * 8), 512)
    np_ = -(-max(n, 1) // block_rows) * block_rows
    x = jnp.full((np_, lp), -jnp.inf, jnp.float32)
    x = x.at[:n, :l].set(cls.astype(jnp.float32))
    grid = (np_ // block_rows,)
    best, idx = pl.pallas_call(
        functools.partial(_class_reduce_kernel, l=l),
        out_shape=(jax.ShapeDtypeStruct((np_, _LANE), jnp.float32),
                   jax.ShapeDtypeStruct((np_, _LANE), jnp.int32)),
        grid=grid,
        in_specs=[pl.BlockSpec((block_rows, lp), lambda i: (i, 0))],
        out_specs=(pl.BlockSpec((block_rows, _LANE), lambda i: (i, 0)),
                   pl.BlockSpec((block_rows, _LANE), lambda i: (i, 0))),
        interpret=interpret,
    )(x)
    return best[:n, 0].astype(cls.dtype), idx[:n, 0]


# --------------------------------------------------------------------------- #
# segment_colorize: per-pixel argmax + RGBA palette lookup
# --------------------------------------------------------------------------- #

def segment_colorize_reference(x: jax.Array, palette: Any,
                               pre_argmaxed: bool = False) -> jax.Array:
    pal = jnp.asarray(palette)
    classes = x.astype(jnp.int32) if pre_argmaxed else jnp.argmax(x, axis=-1)
    return jnp.take(pal, classes.astype(jnp.int32), axis=0)


def _colorize_kernel(c_ref, pal_ref, o_ref):
    cid = c_ref[...][:, 0:1]                          # (bp, 1) int32
    iota = jax.lax.broadcasted_iota(jnp.int32, (cid.shape[0], 256), 1)
    onehot = (cid == iota).astype(jnp.float32)        # (bp, 256)
    out = jnp.dot(onehot, pal_ref[...],
                  preferred_element_type=jnp.float32)  # (bp, 128)
    # palette entries are <256 and exact in f32, so the hop is lossless
    o_ref[...] = out.astype(jnp.int32).astype(jnp.uint8)


def _argmax_colorize_kernel(x_ref, pal_ref, o_ref, *, c: int):
    x = x_ref[...]                                    # (bp, cp) f32
    best = jnp.max(x, axis=1, keepdims=True)
    iota1 = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    cid = jnp.min(jnp.where(x == best, iota1, c), axis=1, keepdims=True)
    iota2 = jax.lax.broadcasted_iota(jnp.int32, (x.shape[0], 256), 1)
    onehot = (cid == iota2).astype(jnp.float32)
    out = jnp.dot(onehot, pal_ref[...],
                  preferred_element_type=jnp.float32)
    o_ref[...] = out.astype(jnp.int32).astype(jnp.uint8)


def segment_colorize(x: jax.Array, palette: Any, pre_argmaxed: bool = False,
                     interpret: bool = False) -> jax.Array:
    """(..., C) logits (or (...) class ids when pre_argmaxed) → (..., 4)
    RGBA uint8 via a (256, 4) palette, fused argmax+gather on device.

    The palette gather runs as a one-hot matmul on the MXU — palette
    values are uint8 (< 256, exact in f32), so the result is identical
    to ``palette[argmax(x, -1)]`` on host.
    """
    if not (interpret or _on_tpu()):
        return segment_colorize_reference(x, palette, pre_argmaxed)
    if _profile.KERNEL_HOOK is not None:  # trace-time kernel label
        _profile.KERNEL_HOOK("pallas.segment_colorize", x.shape, x.dtype)
    from jax.experimental import pallas as pl

    pal = jnp.zeros((256, _LANE), jnp.float32)
    pal_np = np.asarray(palette)
    pal = pal.at[:pal_np.shape[0], :pal_np.shape[1]].set(
        jnp.asarray(pal_np, jnp.float32))
    if pre_argmaxed:
        lead = x.shape
        flat = x.reshape(-1).astype(jnp.int32)
        p = flat.shape[0]
        block_rows = min(max(32, -(-p // 32) * 32), 512)
        pp = -(-max(p, 1) // block_rows) * block_rows
        cids = jnp.zeros((pp, _LANE), jnp.int32).at[:p, 0].set(flat)
        kernel = _colorize_kernel
        inp = cids
        in_block = (block_rows, _LANE)
    else:
        lead = x.shape[:-1]
        c = x.shape[-1]
        flat = x.reshape(-1, c)
        p = flat.shape[0]
        cp = -(-c // _LANE) * _LANE
        block_rows = min(max(32, -(-p // 32) * 32), 512)
        pp = -(-max(p, 1) // block_rows) * block_rows
        xpad = jnp.full((pp, cp), -jnp.inf, jnp.float32)
        xpad = xpad.at[:p, :c].set(flat.astype(jnp.float32))
        kernel = functools.partial(_argmax_colorize_kernel, c=c)
        inp = xpad
        in_block = (block_rows, cp)
    grid = (pp // block_rows,)
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((pp, _LANE), jnp.uint8),
        grid=grid,
        in_specs=[pl.BlockSpec(in_block, lambda i: (i, 0)),
                  pl.BlockSpec((256, _LANE), lambda i: (0, 0))],
        out_specs=pl.BlockSpec((block_rows, _LANE), lambda i: (i, 0)),
        interpret=interpret,
    )(inp, pal)
    return out[:p, :4].reshape(tuple(lead) + (4,))


# --------------------------------------------------------------------------- #
# dequant_gelu_requant: w8a8 MLP inner epilogue, int8 end-to-end
# --------------------------------------------------------------------------- #

def dequant_gelu_requant_reference(y: jax.Array, xs: jax.Array, ws: jax.Array,
                                   out_dtype=jnp.bfloat16
                                   ) -> Tuple[jax.Array, jax.Array]:
    """int32 accumulator → dequant → gelu → per-row int8 requant.

    Composition of ops.int8's unfused pieces, kept bit-exact: the
    dequant/cast matches ``int8_matmul``'s rescale, the requant matches
    ``quant_act`` (same absmax/scale/clip math — change in lockstep).
    """
    h = jax.nn.gelu((y.astype(jnp.float32) * xs * ws).astype(out_dtype))
    xf = h.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
    s = jnp.where(absmax == 0.0, 1.0, absmax / 127.0)
    q = jnp.clip(jnp.round(xf / s), -127, 127).astype(jnp.int8)
    return q, s


def _dgr_kernel(y_ref, xs_ref, ws_ref, q_ref, s_ref, *, out_dtype):
    y = y_ref[...].astype(jnp.float32)                # (br, fp)
    xs = xs_ref[...][:, 0:1]                          # (br, 1)
    ws = ws_ref[...][0:1, :]                          # (1, fp)
    h = (y * xs * ws).astype(out_dtype)
    xf = jax.nn.gelu(h).astype(jnp.float32)
    # padded columns carry ws=0 → h=0 → gelu(0)=0: no effect on absmax
    absmax = jnp.max(jnp.abs(xf), axis=1, keepdims=True)
    s = jnp.where(absmax == 0.0, 1.0, absmax / 127.0)
    q_ref[...] = jnp.clip(jnp.round(xf / s), -127, 127).astype(jnp.int8)
    s_ref[...] = jnp.broadcast_to(s, s_ref.shape)


def dequant_gelu_requant(y: jax.Array, xs: jax.Array, ws: jax.Array,
                         out_dtype=jnp.bfloat16, interpret: bool = False
                         ) -> Tuple[jax.Array, jax.Array]:
    """Fused w8a8 MLP inner epilogue.

    ``y`` is the (..., F) int32 GEMM accumulator, ``xs`` the (..., 1)
    activation scales, ``ws`` the (F,) weight scales. Returns the
    requantized (..., F) int8 activations and their (..., 1) scales, so
    the second GEMM consumes int8 directly — no f32 round trip in HBM.
    """
    if not (interpret or _on_tpu()):
        return dequant_gelu_requant_reference(y, xs, ws, out_dtype)
    if _profile.KERNEL_HOOK is not None:  # trace-time kernel label
        _profile.KERNEL_HOOK("pallas.dequant_gelu_requant", y.shape, y.dtype)
    from jax.experimental import pallas as pl

    lead = y.shape[:-1]
    f = y.shape[-1]
    y2 = y.reshape(-1, f)
    r = y2.shape[0]
    fp = -(-f // _LANE) * _LANE
    block_rows = min(max(32, -(-max(r, 1) // 32) * 32), 256)
    rp = -(-max(r, 1) // block_rows) * block_rows
    ypad = jnp.zeros((rp, fp), jnp.int32).at[:r, :f].set(y2)
    xspad = jnp.zeros((rp, _LANE), jnp.float32).at[:r, 0].set(
        xs.reshape(-1).astype(jnp.float32))
    wspad = jnp.zeros((8, fp), jnp.float32).at[0, :f].set(
        ws.astype(jnp.float32))
    grid = (rp // block_rows,)
    q, s = pl.pallas_call(
        functools.partial(_dgr_kernel, out_dtype=out_dtype),
        out_shape=(jax.ShapeDtypeStruct((rp, fp), jnp.int8),
                   jax.ShapeDtypeStruct((rp, _LANE), jnp.float32)),
        grid=grid,
        in_specs=[pl.BlockSpec((block_rows, fp), lambda i: (i, 0)),
                  pl.BlockSpec((block_rows, _LANE), lambda i: (i, 0)),
                  pl.BlockSpec((8, fp), lambda i: (0, 0))],
        out_specs=(pl.BlockSpec((block_rows, fp), lambda i: (i, 0)),
                   pl.BlockSpec((block_rows, _LANE), lambda i: (i, 0))),
        interpret=interpret,
    )(ypad, xspad, wspad)
    return (q[:r, :f].reshape(tuple(lead) + (f,)),
            s[:r, :1].reshape(tuple(lead) + (1,)))
