"""Pallas TPU kernels for the streaming preprocessing hot path.

The converter→transform→filter prologue is HBM-bandwidth-bound: read uint8
frames, normalize, cast to the MXU compute dtype. XLA fuses the elementwise
chain already (ops/fusion.py); these kernels exist for the cases XLA's
default pipeline doesn't schedule optimally and as the in-tree example of
the pallas path (/opt/skills/guides/pallas_guide.md patterns):

  * ``normalize_u8``     — uint8 → (x*scale + bias) in bf16/f32, tiled over
    (8,128)-aligned blocks in VMEM.
  * ``quantize_affine``  — float → uint8 affine quantization (the reverse
    boundary; reference quantized-model pipelines).

Both have jnp reference implementations used as fallback off-TPU and for
correctness tests (pallas interpret mode on CPU).
"""

from __future__ import annotations

import functools
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...obs import profile as _profile


def _on_tpu() -> bool:
    try:
        dev = jax.devices()[0]
    except Exception:  # noqa: BLE001
        return False
    return "tpu" in dev.platform.lower() or "TPU" in str(dev.device_kind)


# --------------------------------------------------------------------------- #
# normalize_u8: y = x.astype(out_dtype) * scale + bias
# --------------------------------------------------------------------------- #

def _normalize_kernel(x_ref, o_ref, *, scale: float, bias: float, out_dtype):
    x = x_ref[...]
    if jnp.issubdtype(x.dtype, jnp.integer):
        # Mosaic has no direct uint8→float32 cast; hop through int32
        # (free on the VPU, verified on v5e). Float inputs must NOT take
        # this hop — it would truncate fractions.
        x = x.astype(jnp.int32)
    x = x.astype(jnp.float32)
    o_ref[...] = (x * scale + bias).astype(out_dtype)


def normalize_u8_reference(x: jax.Array, scale: float, bias: float,
                           out_dtype=jnp.bfloat16) -> jax.Array:
    return (x.astype(jnp.float32) * scale + bias).astype(out_dtype)


def normalize_u8(x: jax.Array, scale: float = 1.0 / 127.5,
                 bias: float = -1.0, out_dtype=jnp.bfloat16,
                 interpret: bool = False) -> jax.Array:
    """Normalize a uint8 tensor on the VPU via pallas; falls back to the jnp
    path when not on TPU (unless interpret=True for testing)."""
    if not (interpret or _on_tpu()):
        return normalize_u8_reference(x, scale, bias, out_dtype)
    if _profile.KERNEL_HOOK is not None:  # trace-time kernel label
        _profile.KERNEL_HOOK("pallas.normalize_u8", x.shape, x.dtype)
    from jax.experimental import pallas as pl

    orig_shape = x.shape
    flat = x.reshape(-1)
    n = flat.shape[0]
    lane = 128
    sublane = 32  # uint8 min tile height
    block = sublane * lane
    padded = -(-n // block) * block
    if padded != n:
        flat = jnp.pad(flat, (0, padded - n))
    tiled = flat.reshape(-1, lane)
    rows = tiled.shape[0]
    block_rows = min(rows, 512)
    grid = (-(-rows // block_rows),)
    out = pl.pallas_call(
        functools.partial(_normalize_kernel, scale=float(scale),
                          bias=float(bias), out_dtype=out_dtype),
        out_shape=jax.ShapeDtypeStruct((rows, lane), out_dtype),
        grid=grid,
        in_specs=[pl.BlockSpec((block_rows, lane), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((block_rows, lane), lambda i: (i, 0)),
        interpret=interpret,
    )(tiled)
    return out.reshape(-1)[:n].reshape(orig_shape)


# --------------------------------------------------------------------------- #
# quantize_affine: q = clip(round(x / scale) + zero_point, 0, 255) as uint8
# --------------------------------------------------------------------------- #

def _quantize_kernel(x_ref, o_ref, *, inv_scale: float, zero_point: int):
    x = x_ref[...].astype(jnp.float32)
    q = jnp.round(x * inv_scale) + zero_point
    # float32→uint8 is unsupported on Mosaic; clamp then hop through int32
    o_ref[...] = jnp.clip(q, 0, 255).astype(jnp.int32).astype(jnp.uint8)


def quantize_affine_reference(x: jax.Array, scale: float,
                              zero_point: int = 0) -> jax.Array:
    q = jnp.round(x.astype(jnp.float32) / scale) + zero_point
    return jnp.clip(q, 0, 255).astype(jnp.uint8)


def quantize_affine(x: jax.Array, scale: float, zero_point: int = 0,
                    interpret: bool = False) -> jax.Array:
    if not (interpret or _on_tpu()):
        return quantize_affine_reference(x, scale, zero_point)
    if _profile.KERNEL_HOOK is not None:  # trace-time kernel label
        _profile.KERNEL_HOOK("pallas.quantize_affine", x.shape, x.dtype)
    from jax.experimental import pallas as pl

    orig_shape = x.shape
    flat = x.reshape(-1)
    n = flat.shape[0]
    lane = 128
    block = 8 * lane
    padded = -(-n // block) * block
    if padded != n:
        flat = jnp.pad(flat, (0, padded - n))
    tiled = flat.reshape(-1, lane)
    rows = tiled.shape[0]
    block_rows = min(rows, 512)
    grid = (-(-rows // block_rows),)
    out = pl.pallas_call(
        functools.partial(_quantize_kernel, inv_scale=1.0 / float(scale),
                          zero_point=int(zero_point)),
        out_shape=jax.ShapeDtypeStruct((rows, lane), jnp.uint8),
        grid=grid,
        in_specs=[pl.BlockSpec((block_rows, lane), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((block_rows, lane), lambda i: (i, 0)),
        interpret=interpret,
    )(tiled)
    return out.reshape(-1)[:n].reshape(orig_shape)
