"""Blockwise (flash) causal attention as a Pallas TPU kernel.

The transformer prefill/decode hot op. The dense path materialises the
(L, L) score matrix in HBM; this kernel streams K/V blocks through VMEM
with the online-softmax recurrence, so memory is O(Bq·Bk) per core and
the matmuls stay on the MXU (jnp.dot with preferred_element_type=f32).

Grid layout: ``(batch·heads, q_blocks, k_blocks)`` — the k dimension is
an ACCUMULATION axis: scratch (o, m, l) lives in VMEM across the k steps
(TPU grids execute sequentially over the last axis), initialised at
``ki == 0`` and finalised into the output block at the last step.
Causal masking is two-level: whole k-blocks strictly above the diagonal
are skipped via ``pl.when``, the diagonal block applies the per-element
mask.

Off-TPU (tests, CPU mesh) the same kernel runs in interpret mode.

Reference equivalent: the reference has no attention kernels (its models
are CNNs served by vendor runtimes); this is TPU-first scope from
SURVEY §7 (long-context machinery) — the single-device complement of
parallel/ring.py's cross-chip ring.
"""

from __future__ import annotations

import functools
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ... import tune as _tune
from ...obs import profile as _profile
from .preprocess import _on_tpu

_NEG_INF = -1e30  # mask value; finite so (m - m) stays NaN-free

try:  # pallas is part of jax, but keep the module importable without it
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pl = None
    pltpu = None


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *,
                  block_q: int, block_k: int, n_kblocks: int, causal: bool,
                  true_len: int, sm_scale: float, normalize: bool = True):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # whole block strictly above the causal diagonal: contributes nothing
    run = jnp.logical_or(not causal,
                         ki * block_k <= qi * block_q + block_q - 1)

    @pl.when(run)
    def _step():
        q = q_ref[0]                    # (block_q, d)
        k = k_ref[0]                    # (block_k, d)
        v = v_ref[0]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)
        s = s * np.float32(sm_scale)
        cols = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        valid = cols < true_len  # padded keys must never win the softmax
        if causal:
            rows = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            valid = jnp.logical_and(valid, rows >= cols)
        s = jnp.where(valid, s, _NEG_INF)
        m_prev = m_ref[:]               # (block_q, 1)
        l_prev = l_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:] = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jnp.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32)
        m_ref[:] = m_new

    @pl.when(ki == n_kblocks - 1)
    def _finalize():
        if normalize:
            denom = jnp.maximum(l_ref[:], 1e-30)
            o_ref[0] = (acc_ref[:] / denom).astype(o_ref.dtype)
        else:  # residual mode: the UNNORMALIZED accumulator is the output
            o_ref[0] = acc_ref[:].astype(o_ref.dtype)


def _flash_kernel_residual(q_ref, k_ref, v_ref, o_ref, m_out_ref, l_out_ref,
                           acc_ref, m_ref, l_ref, *, block_q: int,
                           block_k: int, n_kblocks: int, causal: bool,
                           true_len: int, sm_scale: float):
    """Same online-softmax recurrence, but emits the UNNORMALIZED
    accumulator plus the per-row softmax residuals (rowmax m, normalizer
    l) so partial attentions over disjoint key sets merge exactly (ring
    attention steps) without a divide/re-multiply round trip."""
    _flash_kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref,
                  block_q=block_q, block_k=block_k, n_kblocks=n_kblocks,
                  causal=causal, true_len=true_len, sm_scale=sm_scale,
                  normalize=False)
    ki = pl.program_id(2)

    @pl.when(ki == n_kblocks - 1)
    def _emit_residuals():
        m_out_ref[0] = m_ref[:]
        l_out_ref[0] = l_ref[:]


def _pick_block(lp: int, want: int) -> int:
    """Largest exact divisor of ``lp`` (a multiple of 128) that is
    <= ``want``, preferring lane-aligned multiples of 128. Keeping
    blocks as divisors of the padded length means no lcm re-padding —
    a 640-long sequence gets 128-wide blocks, not a blow-up to
    lcm(512, 640). Requests below 128 (tests, ring steps over short
    shards) get the largest plain divisor <= the request, so explicit
    small blocks still exercise multi-block tiling."""
    m = lp // 128
    best = 0
    for d in range(1, m + 1):
        if m % d == 0 and d * 128 <= want:
            best = d * 128
    if best:
        return best
    for d in range(1, min(want, lp) + 1):
        if lp % d == 0:
            best = d
    return best or 1


#: the candidate grid the autotuner sweeps/ranks — exactly the
#: FLASH_TUNE_r05 hand-sweep grid, so a tuner pick can never be worse
#: than the best hand-swept point on the same hardware
_TUNE_GRID = ((128, 128), (256, 256), (512, 512), (512, 1024),
              (1024, 1024))
#: hand-swept default (FLASH_TUNE_r05 winner) — what every call gets
#: when the tuner is off or has nothing better
_DEFAULT_BLOCKS = (512, 1024)


def _block_features(b: int, h: int, L: int, d: int, itemsize: int):
    """Per-candidate (flops, bytes) for the cost model: FLOPs are
    block-independent; HBM traffic is not — each q block streams the
    whole K/V once, so K/V re-reads scale with Lp/block_q, and q/o
    re-reads with Lp/block_k staying resident. A coarse roofline, but
    it orders the grid the same way the hand sweep did."""
    Lp = -(-L // 128) * 128
    flops = 4.0 * b * h * Lp * Lp * d  # qk^T + pv, causal ~x0.5 folds
    # into the constant and cancels in ranking

    def features(cand):
        bq, bk = cand
        nq = max(Lp // max(min(bq, Lp), 1), 1)
        kv_traffic = 2.0 * b * h * nq * Lp * d * itemsize
        qo_traffic = 2.0 * b * h * Lp * d * itemsize
        return flops, kv_traffic + qo_traffic

    return features


def _tuned_blocks(q, k, v, causal: bool, interpret: bool):
    """Resolve (block_q, block_k) through the autotuner. Store/model
    hits are free; with neither, a bounded measured sweep times the
    candidate grid on throwaway arrays of the caller's shape — safe
    even while tracing, because the sweep inputs are concrete (jax
    executes them eagerly) and the recursive calls pass explicit
    blocks, which never re-enter the tuner."""
    tn = _tune.TUNE_HOOK
    if tn is None:
        return _DEFAULT_BLOCKS
    b, h, L, d = q.shape
    sig = _tune.shape_sig(("b", b), ("h", h), ("l", L), ("d", d),
                          ("c", int(causal)))
    dev = "interpret" if interpret else _tune.device_kind()
    dt = q.dtype

    def measure(cand):
        bq, bk = cand
        qq = jnp.ones((b, h, L, d), dt)
        kk = jnp.ones((b, h, L, d), dt)
        vv = jnp.ones((b, h, L, d), dt)
        flash_attention(qq, kk, vv, causal=causal, block_q=bq,
                        block_k=bk,
                        interpret=interpret).block_until_ready()  # warm
        t0 = time.perf_counter()
        flash_attention(qq, kk, vv, causal=causal, block_q=bq,
                        block_k=bk,
                        interpret=interpret).block_until_ready()
        return time.perf_counter() - t0

    cand = tn.pick("flash_blocks", dev, "pallas.flash_attention", sig,
                   candidates=_TUNE_GRID, default=_DEFAULT_BLOCKS,
                   measure=measure,
                   features=_block_features(b, h, L, d, dt.itemsize))
    try:
        bq, bk = cand  # store round-trips tuples as lists
        return int(bq), int(bk)
    except (TypeError, ValueError):
        return _DEFAULT_BLOCKS


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = True,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    interpret: Optional[bool] = None,
                    return_residuals: bool = False,
                    _force_pad_d: bool = False):
    """Causal (or full) attention over ``(B, H, L, D)`` tensors.

    Sequence length is padded up to a block multiple internally (padded
    keys are masked via an explicit length mask), and on real TPUs a
    head dim that is not a multiple of the 128-wide lanes is zero-padded
    internally too (score-neutral; padded v columns sliced off, softmax
    scale from the true head dim) — callers never pad anything.

    ``block_q``/``block_k`` default to the FLASH_TUNE_r05 hand-swept
    512/1024 — unless the autotuner hook is installed, in which case
    unset blocks resolve through its store/model/sweep (docs/tuning.md).
    Explicit values always win and never consult the tuner.

    Precision model: scores and the output accumulate in f32; the
    softmax weights are rounded to v's dtype before the PV matmul (the
    standard flash configuration). With bf16 inputs this differs from a
    full-f32 dense computation by ~1e-2 relative.
    """
    if pl is None:  # pragma: no cover
        raise RuntimeError("pallas unavailable in this jax build")
    if _profile.KERNEL_HOOK is not None:  # trace-time kernel label
        _profile.KERNEL_HOOK("pallas.flash_attention", q.shape, q.dtype)
    if interpret is None:
        interpret = not _on_tpu()
    if block_q is None or block_k is None:
        tq, tk = _tuned_blocks(q, k, v, causal, interpret)
        block_q = tq if block_q is None else block_q
        block_k = tk if block_k is None else block_k
    b, h, L, d_orig = q.shape
    sm_scale = 1.0 / float(np.sqrt(d_orig))  # from the TRUE head dim
    d = d_orig
    if (not interpret or _force_pad_d) and d % 128:
        # real-TPU lanes are 128-wide: zero-pad the head dim (zero q/k
        # columns add nothing to the scores; zero v columns are sliced
        # off at return). sm_scale above already uses the true d.
        dpad = -(-d // 128) * 128 - d
        q = jnp.pad(q, ((0, 0), (0, 0), (0, 0), (0, dpad)))
        k = jnp.pad(k, ((0, 0), (0, 0), (0, 0), (0, dpad)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, 0), (0, dpad)))
        d = q.shape[-1]
    # pad the sequence up to a lane-tile multiple, then pick blocks as
    # exact divisors of the padded length (<= the requested sizes): both
    # blocks always tile Lp exactly, so no second lcm padding pass
    Lp = -(-L // 128) * 128
    bq = _pick_block(Lp, min(block_q, Lp))
    bk = _pick_block(Lp, min(block_k, Lp))
    if Lp != L:
        pad = Lp - L
        q = jnp.pad(q, ((0, 0), (0, 0), (0, pad), (0, 0)))
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
    n_q = Lp // bq
    n_k = Lp // bk
    assert n_q * bq == Lp and n_k * bk == Lp
    bh = b * h
    qf = q.reshape(bh, Lp, d)
    kf = k.reshape(bh, Lp, d)
    vf = v.reshape(bh, Lp, d)

    kfn = _flash_kernel_residual if return_residuals else _flash_kernel
    kernel = functools.partial(
        kfn, block_q=bq, block_k=bk, n_kblocks=n_k, causal=causal,
        true_len=L, sm_scale=sm_scale)
    o_spec = pl.BlockSpec((1, bq, d), lambda s, i, j: (s, i, 0))
    r_spec = pl.BlockSpec((1, bq, 1), lambda s, i, j: (s, i, 0))
    o_shape = jax.ShapeDtypeStruct(
        (bh, Lp, d), jnp.float32 if return_residuals else q.dtype)
    r_shape = jax.ShapeDtypeStruct((bh, Lp, 1), jnp.float32)
    result = pl.pallas_call(
        kernel,
        grid=(bh, n_q, n_k),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda s, i, j: (s, i, 0)),
            pl.BlockSpec((1, bk, d), lambda s, i, j: (s, j, 0)),
            pl.BlockSpec((1, bk, d), lambda s, i, j: (s, j, 0)),
        ],
        out_specs=[o_spec, r_spec, r_spec] if return_residuals else o_spec,
        out_shape=[o_shape, r_shape, r_shape] if return_residuals
        else o_shape,
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
        ],
        # batch·heads and q-blocks are independent; only the k axis is an
        # accumulation (scratch carries across it) and must stay ordered
        compiler_params=getattr(pltpu, "CompilerParams",
                                getattr(pltpu, "TPUCompilerParams", None))(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qf, kf, vf)
    if return_residuals:
        acc, m_out, l_out = result
        return (acc.reshape(b, h, Lp, d)[:, :, :L, :d_orig],
                m_out.reshape(b, h, Lp)[:, :, :L],
                l_out.reshape(b, h, Lp)[:, :, :L])
    return result.reshape(b, h, Lp, d)[:, :, :L, :d_orig]
