"""Pipeline graph fusion: compile transform chains INTO the filter's XLA
program.

The reference executes each element's math separately (Orc kernels per
tensor_transform, then the NN backend's own runtime). On TPU that costs one
dispatch + one HBM round-trip per element. This pass rewrites linear
``tensor_transform* → tensor_filter(xla)`` chains so the composed transform
functions become a preprocessing stage *inside* the filter's jit — XLA fuses
them into the model's first kernels (elementwise ops ride along with the
first conv's HBM read), and per-frame Python overhead drops to a single
dispatch.

Applied automatically in ``Pipeline.start()`` (disable with
``pipeline.auto_fuse = False``). Fused transforms stay in the graph for
caps negotiation but forward buffers untouched.
"""

from __future__ import annotations

from typing import Any, List

from ..core.log import logger

log = logger("fusion")


def fuse_chains(pipeline: Any) -> int:
    """Fuse eligible chains; returns number of transforms fused away."""
    from ..elements.filter import TensorFilter
    from ..elements.transform import TensorTransform
    from ..filters.xla import XLAFilter

    fused = 0
    for el in pipeline.elements.values():
        if not isinstance(el, TensorFilter):
            continue
        # only the XLA backend can absorb jax-traceable stages
        try:
            el._open_fw()
        except Exception:  # noqa: BLE001 — config errors surface at start()
            continue
        if not isinstance(el.fw, XLAFilter):
            continue
        chain: List[TensorTransform] = []
        pad = el.sink_pad
        while pad.peer is not None:
            up = pad.peer.element
            if isinstance(up, TensorTransform) and len(up.sink_pads) == 1 \
                    and len(up.src_pads) == 1 and not up._fused:
                chain.append(up)
                pad = up.sink_pad
            else:
                break
        if not chain:
            continue
        chain.reverse()  # upstream → downstream order
        fns = []
        sig = []
        for t in chain:
            fns.append(t.as_jax_fn())
            t._fused = True
            if t.transform_chain:
                sig.append(";".join(f"{m}:{o}" for m, o in t.transform_chain))
            else:
                sig.append(f"{t.mode}:{t.option}")

        def pre(x, _fns=tuple(fns)):
            for f in _fns:
                x = f(x)
            return x

        # structural token: filters sharing a bundle coalesce only when
        # their fused chains compute the same function (sched engine)
        el.fw.set_fused_preprocess(pre, token="|".join(sig))
        fused += len(chain)
        log.info("fused %d transform(s) into %s's XLA program",
                 len(chain), el.name)
    return fused
