"""Epilogue fusion: compile post-filter chains INTO the filter's XLA program.

The downstream mirror of ops.fusion: where that pass absorbs the
``tensor_transform* → tensor_filter`` prologue, this one rewrites linear
``tensor_filter(xla) → tensor_transform*/tensor_converter/tensor_decoder``
tails so the composed post-processing runs as an epilogue stage *inside*
the filter's jit — one dispatch per frame instead of one per element, and
for reduce-capable decoders (SSD box decode + NMS, segmentation
argmax+colorize) the D2H readback shrinks from the full model output to
the reduced result.

Enrolled elements stay in the graph for caps negotiation but forward
buffers untouched (transforms/converters) or consume the pre-reduced
tensor (decoders). Fused output is bit-identical to the unfused chain —
the epilogue applies exactly the fns the elements would have applied.

Applied automatically in ``Pipeline.start()`` after elements are started
(disable with ``pipeline.auto_fuse = False``). Selection is
profiler-driven when profiling is on: ``EPILOGUE_SELECT_HOOK`` is
consulted with the filter and chain labels and can veto a fusion whose
measured chain cost is negligible; when the hook is None (the default)
eligible chains fuse unconditionally.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Tuple

from ..core.log import logger

log = logger("epilogue")

#: Selection hook: ``fn(filter_label, chain_labels) -> bool`` (True =
#: fuse). None (default) = fuse every eligible chain. obs.profile's
#: ``enable()`` installs ``Profiler.epilogue_select`` so fusion decisions
#: follow measured per-element cost; ``disable()`` clears it. Gate every
#: use with a single None check (zero-overhead-when-off contract).
EPILOGUE_SELECT_HOOK: Optional[Callable[[str, List[str]], bool]] = None


def _transform_signature(t: Any) -> str:
    """Structural identity of a transform stage (coalesce-token part:
    same mode/options ⇒ same composed function)."""
    if t.transform_chain:
        inner = ";".join(f"{m}:{o}" for m, o in t.transform_chain)
        return f"transform[{inner}]"
    return f"transform[{t.mode}:{t.option}]"


def fuse_epilogues(pipeline: Any) -> int:
    """Fuse eligible downstream chains; returns stages fused away.

    Runs after ``Element.start()`` (decoder instances must exist) and
    before scheduler attach (the filters' ``coalesce_token`` must be
    final when the engine starts keying batches).
    """
    from ..elements.converter import TensorConverter
    from ..elements.decoder import TensorDecoder
    from ..elements.filter import TensorFilter
    from ..elements.transform import TensorTransform
    from ..filters.xla import XLAFilter

    fused = 0
    for el in pipeline.elements.values():
        if not isinstance(el, TensorFilter) or len(el.src_pads) != 1:
            continue
        try:
            el._open_fw()
        except Exception:  # noqa: BLE001 — config errors surface at start()
            continue
        fw = el.fw
        if not isinstance(fw, XLAFilter):
            continue
        if getattr(fw, "flexible_output", False):
            continue  # bucket ladder emits variable rows; caps won't pin
        if el._out_spec is not None:
            continue  # output combination reorders memories downstream

        stages: List[Tuple[str, Any]] = []
        decoder_stage: Optional[Tuple[Any, Any, Callable]] = None
        pad = el.src_pads[0]
        while pad.peer is not None:
            down = pad.peer.element
            if isinstance(down, TensorTransform) and len(down.sink_pads) == 1 \
                    and len(down.src_pads) == 1 and not down._fused \
                    and not down._fused_post:
                stages.append(("transform", down))
                pad = down.src_pads[0]
                continue
            if isinstance(down, TensorConverter) and len(down.sink_pads) == 1 \
                    and len(down.src_pads) == 1 \
                    and down.mode in (None, "auto") \
                    and int(down.frames_per_tensor) == 1 \
                    and not down._fused_passthrough:
                # static tensors→tensors passthrough: identity math, but
                # enrolling skips the per-frame host round trip
                stages.append(("converter", down))
                pad = down.src_pads[0]
                continue
            if isinstance(down, TensorDecoder) and len(down.sink_pads) == 1:
                dec = down._decoder
                red = dec.epilogue_reduce() if dec is not None else None
                if red is not None and not getattr(dec, "_fused_epilogue",
                                                   False):
                    decoder_stage = (down, dec, red)
            break
        if not stages and decoder_stage is None:
            continue

        labels = [s[1].name for s in stages]
        if decoder_stage is not None:
            labels.append(decoder_stage[0].name)
        if EPILOGUE_SELECT_HOOK is not None \
                and not EPILOGUE_SELECT_HOOK(el.name, labels):
            log.info("epilogue fusion skipped for %s: profiler reports "
                     "chain %s cost negligible", el.name, labels)
            continue

        fns: List[Callable] = []
        sig_parts: List[str] = []
        count = 0
        for kind, t in stages:
            if kind == "transform":
                f = t.as_jax_fn()
                fns.append(lambda outs, _f=f: tuple(_f(y) for y in outs))
                t._fused_post = True
                sig_parts.append(_transform_signature(t))
            else:
                t._fused_passthrough = True
                sig_parts.append("converter[passthrough]")
            count += 1
        if decoder_stage is not None:
            dec_el, dec, red = decoder_stage
            fns.append(lambda outs, _r=red: (_r(outs),))
            dec._fused_epilogue = True
            sig_parts.append(f"decode[{dec.fusion_signature()}]")
            count += 1

        if fns:
            def post(outs, _fns=tuple(fns)):
                for f in _fns:
                    outs = f(outs)
                return outs

            fw.set_fused_epilogue(post, token="|".join(sig_parts))
        fused += count
        log.info("fused %d epilogue stage(s) into %s's XLA program (%s)",
                 count, el.name, "|".join(sig_parts))
    return fused
